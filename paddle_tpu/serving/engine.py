"""The continuous-batching LLM inference engine.

Architecture (prefill/decode split over a slotted static-shape cache):

* **Batched fused prefill** — admission groups queued requests that
  share a prefill bucket (``Scheduler.pop_batch``, bounded reorder
  window) and prefills the whole group in ONE ``[lanes, bucket]``
  compiled dispatch: each lane writes its prompt's k/v into its slot
  row and samples its first token.  Suffixes are right-padded to
  power-of-two length buckets and the lane count is bucketed the same
  way, so there is exactly one compiled prefill program per
  (lane-bucket, length-bucket) pair, reused by every admission batch
  that falls in it (heterogeneous prompt lengths and batch sizes stop
  being retrace sources).  Padding lanes carry a ``valid=False`` flag
  and spare slot ids: they identity-write their rows, so one program
  serves every real batch size in the lane bucket.
* **Prefix KV reuse** — a block-granular radix store over prompt token
  ids (``prefix_cache.py``; RadixAttention's reuse structure over
  vLLM-style fixed-size blocks) maps cached prefixes to a device-
  resident block pool.  A request whose prompt extends a cached prefix
  gathers the cached blocks into its slot row INSIDE the prefill
  program (``pool[block_ids]`` is traced, not dispatched) and prefills
  only the suffix; after prefill, the new full blocks of its prompt are
  scattered back into the pool with one compiled copy per admission
  batch.  Blocks are refcounted while a slot borrows them and evicted
  LRU under a byte budget.
* **Horizon-scanned decode** — ONE compiled program advances ALL slot
  rows by ``H`` fused steps: a ``lax.scan`` whose body embeds the last
  token of every slot, runs the model with per-row positions against
  the full ``[num_slots, max_seq_len, kv_heads, head_dim]`` buffers
  (written via ``dynamic_update_slice``), samples per-request tokens
  under per-request ``fold_in(seed, n_generated)`` PRNG, and masks
  retired lanes (EOS / max-tokens detected INSIDE the scan: their
  ``pos``/``counts`` freeze and their sampled tokens harvest as ``-1``).
  Tokens for all ``H`` steps come back in one ``[H, num_slots]`` array —
  one dispatch and one host sync per horizon, instead of one of each per
  token (DECODE_BENCH.json: the per-step driver pays ~1 ms/step of pure
  host dispatch + sync against a 0.77 ms weight roofline).
* **Device-resident engine state** — the per-slot decode state
  (``tokens/pos/counts/active`` plus the loop-invariant
  ``seeds/temps/top_ks/top_ps/eos_ids/limits``) lives on device and is
  updated inside the compiled program; the host re-uploads it only when
  admission changes it (dirty flag), never per step.  Host mirrors are
  maintained from the harvested tokens alone — no extra device reads.
* **Continuous batching** — requests join at horizon boundaries and
  free their slot on EOS/max-tokens; an adaptive policy shrinks the
  horizon toward 1 when the queue is non-empty or a lane is close to
  its token budget (so admission latency and EOS-mask waste stay
  bounded) and grows it toward ``max_horizon`` while the batch is
  stable.  Horizons are power-of-two buckets, so the decode program
  compiles exactly once per distinct bucket.

Every horizon partition of a request's token stream is bitwise-equal:
the scan body is the same jaxpr as a standalone single step, and a
request's k-th token depends only on (its seed, k, its logits).

The engine reuses the model's own Layer code (functionalized through
``use_state``, the TrainStep pattern), so slotted decode is numerically
the decode path models/gpt.py already ships — just with a cache the
compiler can keep static.
"""

from __future__ import annotations

import time
import weakref
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from ..core import tape as _tape
from ..core.tensor import Tensor
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics
from ..observability.span import span as _obs_span
from .kv_cache import SlotKV, SlottedKVCache
from .prefix_cache import PrefixCache
from .sampling import SamplingParams, request_key, sample_batch, sample_token
from .scheduler import Scheduler

# typed registry families the engine publishes into (labeled by engine
# instance so two engines in one process stay distinguishable); the
# legacy flat counters() dict stays as the profiler-facade back-compat
# surface
_SRV_TOKENS = _obs_metrics.counter(
    "serving.tokens_generated", "tokens sampled across prefill+decode")
_SRV_REQS = _obs_metrics.counter(
    "serving.requests_finished", "requests retired (EOS or max-tokens)")
_SRV_DECODE_STEPS = _obs_metrics.counter(
    "serving.decode_steps", "fused decode steps executed")
_SRV_PREFILL = _obs_metrics.counter(
    "serving.prefill_calls", "batched prefill dispatches")
_SRV_PREFILL_REQS = _obs_metrics.counter(
    "serving.prefill_requests", "requests prefilled (across batches)")
_SRV_PREFIX_HIT = _obs_metrics.counter(
    "serving.prefix_hit_tokens",
    "prompt tokens served from the prefix KV cache instead of recomputed")
_SRV_PREFIX_RATIO = _obs_metrics.gauge(
    "serving.prefix_hit_ratio",
    "cumulative prefix-cache hit tokens / admitted prompt tokens")
_SRV_PREFILL_BATCH = _obs_metrics.histogram(
    "serving.prefill_batch_size", "requests co-prefilled per dispatch",
    buckets=(1, 2, 4, 8, 16, 32))
_SRV_WASTED = _obs_metrics.counter(
    "serving.wasted_lane_tokens",
    "masked tokens scanned for lanes that retired mid-horizon")
_SRV_QUEUE = _obs_metrics.gauge(
    "serving.queue_depth", "requests waiting for a slot")
_SRV_ACTIVE = _obs_metrics.gauge(
    "serving.active_slots", "slots currently decoding")
_SRV_UTIL = _obs_metrics.gauge(
    "serving.slot_utilization", "mean active/total slots over decode steps")
_SRV_TPS = _obs_metrics.gauge(
    "serving.tokens_per_s", "generated tokens per engine-busy second")
_SRV_TTFT = _obs_metrics.histogram(
    "serving.ttft_seconds", "submit-to-first-token wall seconds")
_SRV_STEP = _obs_metrics.histogram(
    "serving.step_seconds", "wall seconds per engine step()")
_SRV_HORIZON = _obs_metrics.histogram(
    "serving.horizon", "fused decode steps per compiled horizon dispatch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
# compile/cache families SHARED with jit/api.py: one place answers
# "which function retraced" for both to_static and serving programs
_COMPILE_COUNT = _obs_metrics.counter(
    "jit.compile_count", "to_static trace+compile builds, by function")
_CACHE_HIT = _obs_metrics.counter(
    "jit.cache_hit", "to_static calls served from the jit cache")
_COMPILE_SECONDS = _obs_metrics.histogram(
    "jit.compile_seconds",
    "wall seconds from cache miss to first result, by function")


class CompiledFn:
    """jax.jit wrapper that counts compile-cache hits/misses by input
    signature (shape+dtype of every array leaf, plus the VALUES of any
    static args — a new static horizon bucket is a new program).  The
    miss counter is the engine's observable proof of static-shape
    serving: a multi-request run with heterogeneous prompt lengths must
    show decode misses == number of distinct horizon buckets and prefill
    misses == number of distinct length buckets.  Hits/misses also land
    on the typed registry (``jit.compile_count`` / ``jit.cache_hit``
    labeled ``fn=name``) and every miss leaves a retrace-cause event plus
    a compile begin/end pair on the timeline."""

    def __init__(self, fn, donate_argnums=(), name=None, static_argnums=()):
        self._jit = jax.jit(fn, donate_argnums=donate_argnums,
                            static_argnums=static_argnums)
        self._name = name or getattr(fn, "__name__", "fn")
        self._static = tuple(static_argnums)
        self._seen = set()
        self.misses = 0
        self.hits = 0

    @property
    def calls(self):
        return self.hits + self.misses

    def _signature(self, args):
        static = tuple(args[i] for i in self._static if i < len(args))
        dynamic = [a for i, a in enumerate(args) if i not in self._static]
        return static + tuple(
            (tuple(jnp.shape(a)), str(jnp.result_type(a)))
            for a in jax.tree.leaves(dynamic))

    def __call__(self, *args):
        sig = self._signature(args)
        if sig in self._seen:
            self.hits += 1
            _CACHE_HIT.inc(fn=self._name)
            return self._jit(*args)
        self._seen.add(sig)
        self.misses += 1
        _obs_events.instant(
            "jit.retrace", cat="serving", fn=self._name,
            cause=("first_call" if self.misses == 1
                   else "new_input_signature"),
            cached_signatures=len(self._seen) - 1)
        _obs_events.begin("jit.compile", cat="serving", fn=self._name)
        t0 = time.perf_counter()
        try:
            return self._jit(*args)
        finally:
            dt = time.perf_counter() - t0
            _COMPILE_COUNT.inc(fn=self._name)
            _COMPILE_SECONDS.observe(dt, fn=self._name)
            _obs_events.end("jit.compile", cat="serving", fn=self._name,
                            seconds=round(dt, 9))


@dataclass
class EngineConfig:
    num_slots: int = 8
    max_seq_len: int = 256
    #: smallest prefill bucket; prompts pad up to the next power of two
    min_prefill_bucket: int = 8
    #: kv cache dtype; None = the model's parameter dtype
    cache_dtype: object = None
    #: largest number of fused decode steps one compiled dispatch may
    #: scan (power of two; 1 disables horizon decode).  The adaptive
    #: policy picks a bucket in [1, max_horizon] at every boundary.
    max_horizon: int = 8
    #: prefix-cache block size in tokens: full blocks of every admitted
    #: prompt are cached and reused by later prompts sharing the prefix
    #: (0 disables prefix caching)
    prefix_block_size: int = 16
    #: device-byte budget for the prefix-cache block pool; the pool
    #: holds budget // bytes_per_block blocks, LRU-evicted when full
    prefix_cache_bytes: int = 8 << 20
    #: admission reorder window: a queued request is never overtaken by
    #: more than this many later-submitted requests when admission
    #: groups same-bucket prompts into one prefill dispatch (0 = strict
    #: FIFO, co-batching only contiguous same-bucket runs)
    reorder_window: int = 8


class Engine:
    """Submit/step/generate over a causal-LM Layer (GPTForCausalLM /
    LlamaForCausalLM or anything with ``.model``, ``.config`` and
    ``._logits``)."""

    _instances = 0

    def __init__(self, model, config=None, register_profiler=True):
        self.model = model
        self.config = config or EngineConfig()
        model.eval()
        mc = model.config
        self._state_names = list(model.state_dict().keys())
        sd = model.state_dict()
        self._state_arrays = [sd[n]._data for n in self._state_names]
        cache_dtype = (self.config.cache_dtype
                       or model.model.embed_tokens.weight._data.dtype)
        self.cache = SlottedKVCache(
            num_layers=len(model.model.layers),
            num_slots=self.config.num_slots,
            max_seq_len=self.config.max_seq_len,
            kv_heads=mc.kv_heads, head_dim=mc.head_dim,
            dtype=cache_dtype)
        self.scheduler = Scheduler(self.config.num_slots,
                                   reorder_window=self.config.reorder_window)

        # prefix KV reuse: block-granular radix store over prompt ids +
        # a device-resident block pool the prefill program gathers from.
        # A zero block size / budget degenerates to a scratch-only pool;
        # the compiled prefill keeps the identical structure either way.
        self._block_size = max(1, int(self.config.prefix_block_size) or 16)
        budget = (self.config.prefix_cache_bytes
                  if self.config.prefix_block_size else 0)
        self.prefix = PrefixCache(
            num_layers=len(model.model.layers),
            block_size=self._block_size,
            kv_heads=mc.kv_heads, head_dim=mc.head_dim,
            dtype=cache_dtype, budget_bytes=budget)
        # blocks needed to tile a full slot row (gather pads past the
        # row end; the traced reshape slices back to max_seq_len)
        self._max_blocks = -(-self.config.max_seq_len // self._block_size)
        self._leases = {}            # request_id -> PrefixLease

        # host MIRRORS of the per-slot decode state.  The authoritative
        # copy lives on device between horizons (updated inside the
        # compiled scan); the mirrors exist so admission can rebuild the
        # device arrays when it dirties them, and are maintained from
        # harvested tokens alone — retirement is detected inside the
        # scan, so it never dirties the device state.
        n = self.config.num_slots
        self._tokens = np.zeros(n, np.int32)        # last token per slot
        self._pos = np.zeros(n, np.int32)           # row length per slot
        self._seeds = np.zeros(n, np.uint32)
        self._counts = np.zeros(n, np.int32)        # tokens sampled so far
        self._temps = np.zeros(n, np.float32)
        self._top_ks = np.zeros(n, np.int32)
        self._top_ps = np.ones(n, np.float32)
        self._eos_ids = np.full(n, -1, np.int32)    # -1 = no EOS token
        self._limits = np.zeros(n, np.int32)        # max_new_tokens
        self._active = np.zeros(n, bool)
        self._state_dirty = True
        self._d_tokens = self._d_pos = self._d_counts = None
        self._d_active = None
        self._d_params = None

        # donation buys in-place HBM cache updates on accelerators; CPU
        # would only warn that donation is unimplemented
        donate = jax.default_backend() not in ("cpu",)
        self._decode = CompiledFn(
            self._decode_fn,
            donate_argnums=(1, 2, 3, 4, 11, 12) if donate else (),
            static_argnums=(13,), name="serving.decode")
        self._prefill = CompiledFn(self._prefill_fn,
                                   donate_argnums=(9, 10) if donate else (),
                                   name="serving.prefill")
        self._insert = CompiledFn(self._insert_fn,
                                  donate_argnums=(5, 6) if donate else (),
                                  name="serving.prefix_insert")

        # observability
        self._decode_steps = 0
        self._decode_horizons = 0
        self._host_syncs = 0
        self._decode_harvested = 0
        self._wasted_lane_tokens = 0
        self._horizon_buckets = set()
        self._grow = 1                   # adaptive-horizon growth state
        self._prefill_calls = 0          # compiled prefill DISPATCHES
        self._prefill_requests = 0       # requests prefilled (>= calls)
        self._prefix_hit_tokens = 0
        self._prompt_tokens = 0
        self._tokens_generated = 0
        self._busy_s = 0.0
        self._slot_busy_integral = 0.0   # sum over steps of used/num
        self._finished = 0
        self._ttft_sum = 0.0
        self._ttft_n = 0

        Engine._instances += 1
        self._profiler_name = f"serving.engine{Engine._instances}"
        self._finalizer = None
        if register_profiler:
            from .. import profiler as _profiler

            # the provider must NOT keep the engine alive (a bound method
            # in a process-global registry pins the engine — and its full
            # KV cache — forever): register a weakref-backed provider and
            # let GC unregister it, so repeated engine construction in
            # one process never leaks stale providers into
            # profiler.counters()
            ref = weakref.ref(self)

            def _provider():
                eng = ref()
                return eng.counters() if eng is not None else {}

            _profiler.register_counter_provider(self._profiler_name,
                                                _provider)
            self._finalizer = weakref.finalize(
                self, _profiler.unregister_counter_provider,
                self._profiler_name)

    def close(self):
        """Unregister this engine's counter provider (idempotent; also
        runs automatically when the engine is garbage-collected)."""
        if self._finalizer is not None:
            self._finalizer()

    # ------------------------------------------------------------ pure fns
    def _run_model(self, state_arrays, ids, views):
        """Functionalized forward: raw param arrays + token ids + SlotKV
        views -> (last-position logits [B, vocab], new views)."""
        arrays = dict(zip(self._state_names, state_arrays))
        with _tape.no_grad():
            with self.model.use_state(arrays):
                h, new_views = self.model.model(Tensor(ids), caches=views)
                logits = self.model._logits(h)
        return logits._data, new_views

    def _prefill_fn(self, state_arrays, ids, lengths, prefix_lens, slots,
                    valid, block_ids, pool_k, pool_v, cache_k, cache_v,
                    seeds, temps, top_ks, top_ps):
        """Batched fused prefill: one compiled dispatch prefills a whole
        admission batch.

        ids [L, bucket]      right-padded prompt SUFFIXES (the part not
                             served by the prefix cache)
        lengths [L]          suffix lengths (>= 1: an exact-hit prompt
                             still prefills its final token)
        prefix_lens [L]      cached-prefix lengths (0 on a miss)
        slots [L]            UNIQUE target slot rows; padding lanes get
                             spare slot ids so the scatter stays
                             collision-free
        valid [L]            real-request lanes; padding lanes
                             identity-write their slot row
        block_ids [L, MB]    prefix-pool blocks per lane (0 = scratch)

        Each lane's initial row is gathered from the prefix pool —
        cached-prefix copy is traced INTO this program, not a separate
        dispatch — then the model writes the suffix k/v at
        ``prefix_lens`` and the first token is sampled from the last
        valid position's logits with ``request_key(seed, 0)``, exactly
        as per-request prefill did."""
        bs = self._block_size
        max_seq = self.cache.max_seq_len
        lanes = ids.shape[0]

        def lane_rows(pool):
            # [L, MB, bs, H, D] -> [L, MB*bs, H, D] -> slice to the row
            g = pool[block_ids]
            g = g.reshape(lanes, self._max_blocks * bs,
                          self.cache.kv_heads, self.cache.head_dim)
            return g[:, :max_seq]

        views = [SlotKV(lane_rows(pk), lane_rows(pv), prefix_lens)
                 for pk, pv in zip(pool_k, pool_v)]
        logits, new_views = self._run_model(state_arrays, ids, views)
        last = jax.vmap(
            lambda lg, n: jax.lax.dynamic_index_in_dim(
                lg, n - 1, axis=0, keepdims=False))(logits, lengths)
        keys = jax.vmap(request_key)(seeds, jnp.zeros(lanes, jnp.int32))
        first = jax.vmap(sample_token)(last, keys, temps, top_ks, top_ps)
        mask = valid[:, None, None, None]

        def scatter(cache, rows):
            keep = cache[slots]          # identity content for padding
            return cache.at[slots].set(jnp.where(mask, rows, keep))

        new_k = [scatter(ck, nv.k) for ck, nv in zip(cache_k, new_views)]
        new_v = [scatter(cv, nv.v) for cv, nv in zip(cache_v, new_views)]
        return first, new_k, new_v

    def _insert_fn(self, cache_k, cache_v, src_slots, src_offsets,
                   dst_ids, pool_k, pool_v):
        """Copy freshly prefilled KV blocks into the prefix pool: for
        each entry, the ``block_size`` tokens at block offset
        ``src_offsets[i]`` of slot row ``src_slots[i]`` land in pool
        block ``dst_ids[i]``.  Padding entries target scratch block 0.
        One compiled dispatch covers a whole admission batch (entry
        count is bucketed to a power of two)."""
        bs = self._block_size

        def copy(cache, pool):
            rows = cache[src_slots]              # [T, max_seq, H, D]

            def cut(row, off):
                return jax.lax.dynamic_slice(
                    row, (off * bs, 0, 0), (bs,) + row.shape[1:])

            blocks = jax.vmap(cut)(rows, src_offsets)
            return pool.at[dst_ids].set(blocks)

        return ([copy(c, p) for c, p in zip(cache_k, pool_k)],
                [copy(c, p) for c, p in zip(cache_v, pool_v)])

    def _decode_fn(self, state_arrays, tokens, pos, counts, active,
                   seeds, temps, top_ks, top_ps, eos_ids, limits,
                   cache_k, cache_v, horizon):
        """The horizon-scanned fused decode: ``lax.scan`` over ``horizon``
        fused steps, all slots, static shapes everywhere.  Retirement is
        detected inside the scan — a lane whose sampled token hits its
        EOS id or exhausts its token budget freezes (``pos``/``counts``
        stop advancing, its carried token stops changing) and harvests
        ``-1`` from then on.  Frozen lanes still run the model (their
        k/v writes land at a frozen position in a dead row, overwritten
        by the next prefill into that slot), so every iteration keeps
        the one static shape.  ``horizon`` is static: one compiled
        program per bucket."""

        def body(carry, _):
            tok, p, cnt, act, ck, cv = carry
            views = [SlotKV(k, v, p) for k, v in zip(ck, cv)]
            logits, new_views = self._run_model(state_arrays, tok[:, None],
                                                views)
            nxt = sample_batch(logits[:, 0], seeds, cnt, temps, top_ks,
                               top_ps)
            nxt = jnp.where(act, nxt, tok)
            new_cnt = jnp.where(act, cnt + 1, cnt)
            new_p = jnp.where(act, p + 1, p)
            done = act & ((nxt == eos_ids) | (new_cnt >= limits))
            harvest = jnp.where(act, nxt, -1)
            return ((nxt, new_p, new_cnt, act & ~done,
                     tuple(v.k for v in new_views),
                     tuple(v.v for v in new_views)), harvest)

        init = (tokens, pos, counts, active,
                tuple(cache_k), tuple(cache_v))
        (tok, p, cnt, act, ck, cv), toks = jax.lax.scan(
            body, init, None, length=horizon)
        return (tok, p, cnt, act), list(ck), list(cv), toks

    # ------------------------------------------------------------ buckets
    def _bucket(self, prompt_len):
        b = self.config.min_prefill_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.config.max_seq_len)

    def _lane_bucket(self, n):
        """Static lane count for an n-request prefill batch: the next
        power of two, capped at num_slots (so slot ids stay unique)."""
        lanes = 1
        while lanes < n:
            lanes *= 2
        return min(lanes, self.config.num_slots)

    def _admission_bucket(self, req):
        """The prefill length bucket a request would dispatch in right
        now: its suffix past the cached prefix, padded to a power of
        two, clamped so prefix + bucket fits the slot row.  Used both
        for co-batch grouping (Scheduler.pop_batch) and for sizing the
        actual dispatch."""
        matched = self.prefix.lookup(req.prompt_ids)
        bucket = min(self._bucket(req.prompt_len - matched),
                     self.config.max_seq_len - matched)
        return bucket

    @staticmethod
    def _pow2_floor(x):
        return 1 << (int(x).bit_length() - 1)

    def _resolve_horizon(self, requested=None):
        """Pick the horizon bucket for the next decode dispatch.

        Explicit ``requested`` is clamped to ``[1, max_horizon]`` and
        rounded down to a power of two (the static compile buckets).
        Adaptive (``requested=None``): 1 while the queue is non-empty
        (admit at every boundary) or a lane is within one step of its
        token budget; otherwise grow multiplicatively from the last
        stable horizon toward ``max_horizon``, capped by the smallest
        remaining budget so length-retirement never wastes lane steps
        (EOS remains unpredictable — mid-horizon EOS waste is measured
        by ``serving.wasted_lane_tokens``)."""
        max_h = max(1, int(self.config.max_horizon))
        if requested is not None:
            return self._pow2_floor(min(max(1, int(requested)), max_h))
        if self.scheduler.queue_depth:
            return 1
        rem = min(r.remaining_budget
                  for r in self.scheduler.running.values())
        return self._pow2_floor(max(1, min(max_h, self._grow, rem)))

    # ------------------------------------------------------------ API
    def submit(self, prompt_ids, sampling=None):
        """Queue one request; returns the Request handle (its
        ``output_ids`` fill in as the engine steps)."""
        prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt_ids:
            raise ValueError("empty prompt")
        sampling = sampling or SamplingParams()
        if len(prompt_ids) + sampling.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt_len {len(prompt_ids)} + max_new_tokens "
                f"{sampling.max_new_tokens} exceeds max_seq_len "
                f"{self.config.max_seq_len}")
        req = self.scheduler.submit(prompt_ids, sampling)
        _SRV_QUEUE.set(self.scheduler.queue_depth,
                       engine=self._profiler_name)
        return req

    def admit(self):
        """Run admission + prefill for queued requests without decoding
        (step() calls this; exposed so latency-sensitive callers and
        benchmarks can separate prefill from the decode window).

        Admission pops co-bucketed batches (same suffix bucket after
        prefix matching, bounded reorder window) and prefills each batch
        in ONE compiled dispatch — N same-bucket admissible requests
        cost 1 prefill dispatch, not N."""
        while self.cache.free_slots and self.scheduler.queue_depth:
            batch = self.scheduler.pop_batch(self.cache.free_slots,
                                             bucket_of=self._admission_bucket)
            if not batch:
                break
            self._prefill_batch(batch)

    _admit = admit      # pre-horizon internal name, kept for callers

    def _prefill_batch(self, batch):
        """One compiled prefill dispatch for a co-bucketed admission
        batch: allocate slots, pin cached prefixes, gather + suffix-
        prefill every lane, insert the new blocks into the prefix pool,
        then harvest first tokens and arm the decode state."""
        n = len(batch)
        bucket = max(self._admission_bucket(r) for r in batch)
        lanes = self._lane_bucket(n)
        slots, leases = [], []
        for req in batch:
            slot = self.cache.alloc()
            slots.append(slot)
            self.scheduler.start(req, slot)
            lease = self.prefix.acquire(req.prompt_ids)
            leases.append(lease)
            self._leases[req.request_id] = lease
            req.prefix_hit_tokens = lease.matched_tokens
            _obs_events.instant("serving.slot_alloc", cat="serving",
                                slot=slot, request=req.request_id,
                                prompt_len=req.prompt_len, bucket=bucket,
                                prefix_hit=lease.matched_tokens)
            # async span: a request's life overlaps other requests on
            # this thread, so it pairs by id, not by B/E nesting
            _obs_events.record(
                "serving.request", phase=_obs_events.ASYNC_BEGIN,
                cat="serving", id=req.request_id,
                args={"slot": slot, "prompt_len": req.prompt_len,
                      "prefix_hit_tokens": lease.matched_tokens})

        # lane arrays: real requests first, then padding lanes carrying
        # spare (unique, unprefilled) slot ids and identity writes
        ids = np.zeros((lanes, bucket), np.int32)
        lengths = np.ones(lanes, np.int32)
        prefix_lens = np.zeros(lanes, np.int32)
        block_ids = np.zeros((lanes, self._max_blocks), np.int32)
        valid = np.zeros(lanes, bool)
        seeds = np.zeros(lanes, np.uint32)
        temps = np.zeros(lanes, np.float32)
        top_ks = np.zeros(lanes, np.int32)
        top_ps = np.ones(lanes, np.float32)
        lane_slots = np.zeros(lanes, np.int32)
        spare = iter(sorted(set(range(self.cache.num_slots)) - set(slots)))
        for i in range(lanes):
            if i < n:
                req, lease = batch[i], leases[i]
                suffix = req.prompt_ids[lease.matched_tokens:]
                ids[i, :len(suffix)] = suffix
                lengths[i] = len(suffix)
                prefix_lens[i] = lease.matched_tokens
                block_ids[i, :len(lease.block_ids)] = lease.block_ids
                valid[i] = True
                s = req.sampling
                seeds[i] = np.uint32(s.seed)
                temps[i] = s.temperature
                top_ks[i] = s.top_k
                top_ps[i] = s.top_p
                lane_slots[i] = slots[i]
            else:
                lane_slots[i] = next(spare)

        with _obs_span("serving.prefill_pass", cat="serving",
                       engine=self._profiler_name,
                       event_args={"batch_size": n, "lanes": lanes,
                                   "bucket": bucket}):
            first, new_k, new_v = self._prefill(
                self._state_arrays, jnp.asarray(ids),
                jnp.asarray(lengths), jnp.asarray(prefix_lens),
                jnp.asarray(lane_slots), jnp.asarray(valid),
                jnp.asarray(block_ids),
                self.prefix.pool_k, self.prefix.pool_v,
                self.cache.k, self.cache.v,
                jnp.asarray(seeds), jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps))
        self.cache.rebind(new_k, new_v)
        self._prefill_calls += 1
        self._prefill_requests += n
        name = self._profiler_name
        _SRV_PREFILL.inc(engine=name)
        _SRV_PREFILL_REQS.inc(n, engine=name)
        _SRV_PREFILL_BATCH.observe(n, engine=name)

        # cache the new full blocks of every admitted prompt (reads the
        # freshly written slot rows, BEFORE any later dispatch reuses
        # them); one compiled copy covers the whole batch
        copies = []
        for req, lease, slot in zip(batch, leases, slots):
            for off, dst in self.prefix.insert(req.prompt_ids, lease):
                copies.append((slot, off, dst))
        if copies:
            self._dispatch_insert(copies)

        first_np = np.asarray(first)     # the one prefill host sync
        for i, (req, lease, slot) in enumerate(zip(batch, leases, slots)):
            hit = lease.matched_tokens
            self._prefix_hit_tokens += hit
            self._prompt_tokens += req.prompt_len
            if hit:
                _SRV_PREFIX_HIT.inc(hit, engine=name)
            self._tokens_generated += 1
            _SRV_TOKENS.inc(engine=name)
            tok = int(first_np[i])
            if req.record_token(tok):
                self._retire(req)
                continue
            s = req.sampling
            self._tokens[slot] = tok
            self._pos[slot] = req.prompt_len
            self._seeds[slot] = np.uint32(s.seed)
            self._counts[slot] = req.n_generated
            self._temps[slot] = s.temperature
            self._top_ks[slot] = s.top_k
            self._top_ps[slot] = s.top_p
            self._eos_ids[slot] = -1 if s.eos_token_id is None \
                else int(s.eos_token_id)
            self._limits[slot] = s.max_new_tokens
            self._active[slot] = True
            self._state_dirty = True     # admission is the ONLY host
            # write into device-resident state; retirement is detected
            # inside the scan, so it needs no re-upload

    def _dispatch_insert(self, copies):
        """Scatter new prefix blocks from slot rows into the pool: one
        compiled dispatch per admission batch, entry count padded to a
        power of two (padding targets scratch block 0)."""
        t = 1
        while t < len(copies):
            t *= 2
        src_slots = np.zeros(t, np.int32)
        src_offsets = np.zeros(t, np.int32)
        dst_ids = np.zeros(t, np.int32)
        for i, (slot, off, dst) in enumerate(copies):
            src_slots[i] = slot
            src_offsets[i] = off
            dst_ids[i] = dst
        new_pk, new_pv = self._insert(
            self.cache.k, self.cache.v, jnp.asarray(src_slots),
            jnp.asarray(src_offsets), jnp.asarray(dst_ids),
            self.prefix.pool_k, self.prefix.pool_v)
        self.prefix.rebind(new_pk, new_pv)

    def _retire(self, req):
        self.cache.free(req.slot)
        self.scheduler.finish(req)
        lease = self._leases.pop(req.request_id, None)
        if lease is not None:
            self.prefix.release(lease)   # blocks become evictable again
        self._finished += 1
        self._ttft_sum += req.ttft
        self._ttft_n += 1
        _SRV_REQS.inc(engine=self._profiler_name)
        _SRV_TTFT.observe(req.ttft, engine=self._profiler_name)
        _obs_events.instant("serving.slot_retire", cat="serving",
                            slot=req.slot, request=req.request_id,
                            reason=req.finish_reason,
                            n_generated=req.n_generated)
        _obs_events.record(
            "serving.request", phase=_obs_events.ASYNC_END,
            cat="serving", id=req.request_id,
            args={"reason": req.finish_reason,
                  "n_generated": req.n_generated,
                  "ttft_s": round(req.ttft, 6)})
        # the freed lane keeps its frozen state (matching the device
        # copy, which masked it inside the scan); the mirror only drops
        # the active bit — no re-upload, no parking
        self._active[req.slot] = False

    def _sync_device_state(self):
        """Upload the per-slot state mirrors — only when admission
        dirtied them.  In steady-state decode the device arrays returned
        by the previous horizon are passed straight back in."""
        if not self._state_dirty:
            return
        self._d_tokens = jnp.asarray(self._tokens)
        self._d_pos = jnp.asarray(self._pos)
        self._d_counts = jnp.asarray(self._counts)
        self._d_active = jnp.asarray(self._active)
        self._d_params = tuple(
            jnp.asarray(a) for a in (self._seeds, self._temps,
                                     self._top_ks, self._top_ps,
                                     self._eos_ids, self._limits))
        self._state_dirty = False

    def _dispatch_horizon(self, h):
        """One compiled decode dispatch over ``h`` fused steps; adopts
        the returned device state and returns the harvested ``[h, n]``
        token array AFTER the one blocking host sync."""
        self._sync_device_state()
        seeds, temps, top_ks, top_ps, eos_ids, limits = self._d_params
        (tok, p, cnt, act), new_k, new_v, toks = self._decode(
            self._state_arrays, self._d_tokens, self._d_pos,
            self._d_counts, self._d_active,
            seeds, temps, top_ks, top_ps, eos_ids, limits,
            self.cache.k, self.cache.v, h)
        self.cache.rebind(new_k, new_v)
        self._d_tokens, self._d_pos = tok, p
        self._d_counts, self._d_active = cnt, act
        toks = np.asarray(toks)      # the ONE host sync per horizon
        self._host_syncs += 1
        return toks

    def step(self, horizon=None):
        """One engine iteration: admit queued requests into free slots
        (prefill), then run ONE compiled horizon of fused decode steps
        over every slot.  ``horizon=None`` lets the adaptive policy pick
        the bucket; an explicit value is bucketed to a power of two
        (scanning past a request's retirement is correct — masked — just
        wasteful).  Returns the requests that finished during this
        step."""
        t0 = time.time()
        finished = []
        self.admit()
        active = dict(self.scheduler.running)
        if active:
            h = self._resolve_horizon(horizon)
            self._horizon_buckets.add(h)
            with _obs_span("serving.decode_step", cat="serving",
                           engine=self._profiler_name,
                           event_args={"horizon": h}) as sp:
                toks = self._dispatch_horizon(h)
                harvested, wasted = self._harvest(toks, active, h,
                                                  finished)
                sp.event_args["tokens_harvested"] = harvested
            self._decode_steps += h
            self._decode_horizons += 1
            self._slot_busy_integral += h * len(active) / self.cache.num_slots
            name = self._profiler_name
            _SRV_DECODE_STEPS.inc(h, engine=name)
            _SRV_HORIZON.observe(h, engine=name)
            _SRV_TOKENS.inc(harvested, engine=name)
            if wasted:
                _SRV_WASTED.inc(wasted, engine=name)
            # adaptive growth: stable horizon (nothing retired, nothing
            # waiting) doubles the next one; churn resets to 1
            if finished or self.scheduler.queue_depth:
                self._grow = 1
            else:
                self._grow = min(max(1, int(self.config.max_horizon)),
                                 max(self._grow, h) * 2)
        dt = time.time() - t0
        self._busy_s += dt
        _SRV_STEP.observe(dt, engine=self._profiler_name)
        self._publish_gauges()
        return finished

    def _harvest(self, toks, active, h, finished):
        """Walk the ``[h, num_slots]`` harvested tokens, replaying each
        running request's stream in order: record real tokens, retire on
        EOS/limit (the host check mirrors the in-scan mask), count
        post-retirement ``-1`` lane steps as waste, and keep the host
        mirrors equal to the frozen device state."""
        harvested = wasted = 0
        for slot, req in active.items():
            done = False
            for k in range(h):
                t = int(toks[k, slot])
                if done:
                    wasted += 1
                    continue
                if t < 0:
                    raise RuntimeError(
                        f"horizon mask retired slot {slot} at step {k} "
                        "but the scheduler still runs its request — "
                        "in-scan EOS/limit logic diverged from "
                        "record_token")
                harvested += 1
                self._tokens_generated += 1
                self._tokens[slot] = t
                self._pos[slot] += 1
                if req.record_token(t):
                    self._retire(req)
                    finished.append(req)
                    done = True
                self._counts[slot] = req.n_generated
        self._decode_harvested += harvested
        self._wasted_lane_tokens += wasted
        return harvested, wasted

    def _publish_gauges(self):
        """Refresh the point-in-time typed gauges (once per step — the
        counters/histograms above accumulate incrementally)."""
        name = self._profiler_name
        _SRV_QUEUE.set(self.scheduler.queue_depth, engine=name)
        _SRV_ACTIVE.set(self.cache.used_slots, engine=name)
        if self._decode_steps:
            _SRV_UTIL.set(self._slot_busy_integral / self._decode_steps,
                          engine=name)
        if self._busy_s > 0:
            _SRV_TPS.set(self._tokens_generated / self._busy_s,
                         engine=name)
        if self._prompt_tokens:
            _SRV_PREFIX_RATIO.set(
                self._prefix_hit_tokens / self._prompt_tokens,
                engine=name)

    def run(self):
        """Drain the queue: step until every submitted request finished.
        Returns all requests retired during the drain."""
        out = []
        while self.scheduler.has_work:
            before = self._finished
            out.extend(self.step())
            if self._finished == before and not self.scheduler.running \
                    and self.scheduler.queue_depth:
                raise RuntimeError("engine stalled with queued work")
        return out

    def generate(self, prompts, sampling=None):
        """Convenience wrapper: one prompt (list of ids) or a batch
        (list of lists).  Submits, drains, and returns the generated ids
        — a list per prompt, in submission order."""
        single = bool(prompts) and np.isscalar(prompts[0])
        batch = [prompts] if single else list(prompts)
        if isinstance(sampling, (list, tuple)):
            reqs = [self.submit(p, s) for p, s in zip(batch, sampling)]
        else:
            reqs = [self.submit(p, sampling) for p in batch]
        self.run()
        outs = [r.output_ids for r in reqs]
        return outs[0] if single else outs

    # ------------------------------------------------------------ bench
    def measure_decode_seconds(self, horizon, iters=3):
        """Benchmark hook: best wall seconds for ONE compiled horizon
        dispatch (including its single host sync) over the engine's
        current device state.  Advances the cache/state buffers, so call
        it only after draining — it exists to separate device time from
        the engine's host-side per-horizon overhead."""
        h = self._resolve_horizon(horizon)
        best = None
        for _ in range(iters):
            t0 = time.perf_counter()
            self._dispatch_horizon(h)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    # ------------------------------------------------------------ metrics
    def counters(self):
        """Observability snapshot (also exposed via
        paddle_tpu.profiler.counters())."""
        c = {
            "queue_depth": self.scheduler.queue_depth,
            "active_slots": self.cache.used_slots,
            "num_slots": self.cache.num_slots,
            "requests_finished": self._finished,
            "tokens_generated": self._tokens_generated,
            "decode_steps": self._decode_steps,
            "decode_horizons": self._decode_horizons,
            "decode_calls": self._decode.calls,
            "decode_host_syncs": self._host_syncs,
            "wasted_lane_tokens": self._wasted_lane_tokens,
            "prefill_calls": self._prefill_calls,
            "prefill_requests": self._prefill_requests,
            "prefix_hit_tokens": self._prefix_hit_tokens,
            "prompt_tokens": self._prompt_tokens,
            "prefix_hit_ratio": (
                self._prefix_hit_tokens / self._prompt_tokens
                if self._prompt_tokens else 0.0),
            "decode_compiles": self._decode.misses,
            "decode_cache_hits": self._decode.hits,
            "prefill_compiles": self._prefill.misses,
            "prefill_cache_hits": self._prefill.hits,
            "prefix_insert_calls": self._insert.calls,
        }
        if self._decode_steps:
            c["slot_utilization"] = (self._slot_busy_integral
                                     / self._decode_steps)
        if self._ttft_n:
            c["ttft_avg_s"] = self._ttft_sum / self._ttft_n
        if self._busy_s > 0:
            c["tokens_per_s"] = self._tokens_generated / self._busy_s
        return c

    def stats(self):
        """counters() plus derived stats: the distinct compiled horizon
        buckets, the fraction of scanned lane steps wasted on lanes that
        had already retired mid-horizon, prefix-cache internals, and
        exact TTFT percentiles from the observability reservoir."""
        s = dict(self.counters())
        lane_steps = self._decode_harvested + self._wasted_lane_tokens
        s["wasted_lane_fraction"] = (
            self._wasted_lane_tokens / lane_steps if lane_steps else 0.0)
        s["horizon_buckets"] = sorted(self._horizon_buckets)
        s["next_horizon_growth"] = self._grow
        s["prefix"] = self.prefix.stats()
        if self._ttft_n:
            s["ttft_p50_s"] = _SRV_TTFT.percentile(
                50, engine=self._profiler_name)
            s["ttft_p95_s"] = _SRV_TTFT.percentile(
                95, engine=self._profiler_name)
        return s
