"""The continuous-batching LLM inference engine.

Architecture (prefill/decode split over ONE paged KV block pool):

* **Unified paged KV pool** — all KV lives in a single per-layer
  ``[num_blocks, block_size, kv_heads, head_dim]`` pool
  (``kv_cache.PagedKVCache``): each slot addresses it through a
  host-authoritative ``[num_slots, max_blocks_per_slot]`` block table
  whose live prefix is uploaded before a dispatch only when dirty.
  Blocks are refcounted — a table entry and a prefix-store node each
  hold one reference — so prefix sharing is copy-free and preemption is
  bookkeeping.  Table entries are allocated lazily (admission covers
  the prompt, ``_ensure_blocks`` extends coverage per horizon), so HBM
  scales with LIVE tokens, not ``num_slots * max_seq_len``.
* **Batched fused prefill** — admission groups queued requests that
  share a prefill bucket (``Scheduler.pop_batch``, bounded reorder
  window) and prefills the whole group in ONE ``[lanes, bucket]``
  compiled dispatch: each lane scatters its suffix k/v through its
  block-table row and samples its first token.  Suffixes are
  right-padded to power-of-two length buckets and the lane count is
  bucketed the same way — one compiled prefill program per
  (lane-bucket, length-bucket) pair.  Padding lanes carry an all-zero
  table row, so their writes land in the reserved scratch block 0 and
  no validity masking or spare-slot machinery is needed.
* **Copy-free prefix reuse** — the radix store (``prefix_cache.py``,
  unified-pool mode) holds refcounted blocks of the SAME pool.  A hit
  leases the matched blocks straight into the slot's table
  (``lease_block``: one ``pool.share`` per entry, zero copies); a
  partial tail match is served copy-on-write — the prefill program
  copies that ONE block into the slot's private tail block, then
  overwrites from the divergence offset on.  After prefill, ``adopt()``
  takes shared references on the slot's freshly written private blocks
  — caching new content is host-side refcounting, no gather/scatter
  dispatches at all.
* **Horizon-scanned ragged decode** — ONE compiled program advances ALL
  slots by ``H`` fused steps: a ``lax.scan`` carrying the donated pool,
  whose body embeds the last token of every slot, scatter-writes k/v
  through the (loop-invariant) block tables, runs paged attention over
  ONLY the ``nb`` table-mapped blocks per lane
  (``paged_attention.py``: Pallas kernel on TPU, the nb-invariant XLA
  online-softmax fallback on CPU), samples per-request tokens under
  ``fold_in(seed, n_generated)`` PRNG, and masks retired lanes (EOS /
  max-tokens detected INSIDE the scan: their ``pos``/``counts`` freeze
  and their sampled tokens harvest as ``-1``).  ``nb`` is bucketed to a
  power of two of the deepest live row, so per-step KV traffic tracks
  live sequence length instead of ``max_seq_len`` and the program
  compiles once per ``(horizon, nb)`` bucket (``stats()``:
  ``decode_buckets``); the fallback's exact-zero masking makes outputs
  bitwise-invariant to ``nb``, so re-bucketing as sequences grow never
  perturbs a token.
* **Device-resident engine state** — the per-slot decode state
  (``tokens/pos/counts/active`` plus the loop-invariant
  ``seeds/temps/top_ks/top_ps/eos_ids/limits``) lives on device and is
  updated inside the compiled program; the host re-uploads it only when
  admission changes it (dirty flag), never per step.  Host mirrors are
  maintained from the harvested tokens alone — no extra device reads.
* **Self-drafting speculative decode** — with ``spec_k > 0`` every
  fused step verifies a ``K+1``-token window per lane instead of one
  token: a traced prompt-lookup drafter (``drafter.py``) proposes K
  continuation tokens from the lane's own device-resident token history,
  the model scores all K+1 positions in ONE forward (the verify step is
  a short ragged prefill through the same paged-attention kernel), and
  the lane emits the longest draft prefix whose sampled tokens match,
  plus the model's own next token — 1..K+1 tokens per forward.  Token k
  of a request is ALWAYS sampled from position k's logits under
  ``fold_in(seed, k)``, so greedy and seeded-sampled outputs stay
  bitwise-equal to sequential ``generate()`` for every K.  Rejected
  draft positions write garbage KV at ``pos+n..pos+K`` — but the next
  step's window writes at ``pos' = pos+n`` BEFORE any read reaches
  those positions (write-before-attend), so the garbage is dead on
  arrival.  ``K`` is a static compile bucket like ``horizon``
  (``decode_buckets`` becomes ``(horizon, nb, K)`` triples) and an
  adaptive policy shrinks the dispatch to K=0 (plain decode) when no
  running lane's recent acceptance EMA clears ``spec_accept_floor``.
* **Continuous batching + preemption** — requests join at horizon
  boundaries and release their blocks on EOS/max-tokens; an adaptive
  policy shrinks the horizon toward 1 when the queue is non-empty or a
  lane is near its token budget, and grows it toward ``max_horizon``
  while the batch is stable.  Under block pressure the engine first
  reclaims unpinned prefix blocks, then **preempts** the youngest
  running request (``preempt()``: release blocks + requeue at the
  front; re-admission re-prefills prompt + generated-so-far and the
  fold_in PRNG reproduces its next token bitwise, so swapping an idle
  sequence out and back is invisible in its output).

Every horizon partition of a request's token stream is bitwise-equal:
the scan body is the same jaxpr as a standalone single step, and a
request's k-th token depends only on (its seed, k, its logits).

The engine reuses the model's own Layer code (functionalized through
``use_state``, the TrainStep pattern), so paged decode is numerically
the decode path models/gpt.py already ships — just with a cache the
compiler can keep static.
"""

from __future__ import annotations

import time
import weakref
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from ..core import tape as _tape
from ..core.tensor import Tensor
from ..observability import events as _obs_events
from ..observability import memory as _obs_memory
from ..observability import metrics as _obs_metrics
from ..observability import profiling as _obs_profiling
from ..observability import tracing as _obs_tracing
from ..observability.span import span as _obs_span
from .drafter import draft_tokens, forced_chain
from .faults import (DEGRADE_LEVELS, FAULT_POOL_EXHAUSTED,
                     SITE_ENGINE_ADMIT, _SRV_DEGRADATION, _SRV_SHED)
from .kv_cache import PagedKV, PagedKVCache
from .kv_host_tier import HostKVTier
from .prefix_cache import PrefixCache
from .sampling import (MASK_FLOOR, SamplingParams, request_key,
                       sample_token, sample_window)
from .scheduler import Scheduler
from .structured.grammar import (GrammarSlab, as_grammar_spec,
                                 compile_grammar)

# typed registry families the engine publishes into (labeled by engine
# instance so two engines in one process stay distinguishable); the
# legacy flat counters() dict stays as the profiler-facade back-compat
# surface
_SRV_TOKENS = _obs_metrics.counter(
    "serving.tokens_generated", "tokens sampled across prefill+decode")
_SRV_REQS = _obs_metrics.counter(
    "serving.requests_finished", "requests retired (EOS or max-tokens)")
_SRV_DECODE_STEPS = _obs_metrics.counter(
    "serving.decode_steps", "fused decode steps executed")
_SRV_PREFILL = _obs_metrics.counter(
    "serving.prefill_calls", "batched prefill dispatches")
_SRV_PREFILL_REQS = _obs_metrics.counter(
    "serving.prefill_requests", "requests prefilled (across batches)")
_SRV_PREFIX_HIT = _obs_metrics.counter(
    "serving.prefix_hit_tokens",
    "prompt tokens served from the prefix KV cache instead of recomputed")
_SRV_PREFIX_RATIO = _obs_metrics.gauge(
    "serving.prefix_hit_ratio",
    "cumulative prefix-cache hit tokens / admitted prompt tokens")
_SRV_PREFILL_BATCH = _obs_metrics.histogram(
    "serving.prefill_batch_size", "requests co-prefilled per dispatch",
    buckets=(1, 2, 4, 8, 16, 32))
_SRV_WASTED = _obs_metrics.counter(
    "serving.wasted_lane_tokens",
    "masked tokens scanned for lanes that retired mid-horizon")
_SRV_QUEUE = _obs_metrics.gauge(
    "serving.queue_depth", "requests waiting for a slot")
_SRV_ACTIVE = _obs_metrics.gauge(
    "serving.active_slots", "slots currently decoding")
_SRV_UTIL = _obs_metrics.gauge(
    "serving.slot_utilization", "mean active/total slots over decode steps")
_SRV_TPS = _obs_metrics.gauge(
    "serving.tokens_per_s", "generated tokens per engine-busy second")
_SRV_TTFT = _obs_metrics.histogram(
    "serving.ttft_seconds", "submit-to-first-token wall seconds")
_SRV_STEP = _obs_metrics.histogram(
    "serving.step_seconds", "wall seconds per engine step()")
_SRV_HORIZON = _obs_metrics.histogram(
    "serving.horizon", "fused decode steps per compiled horizon dispatch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
_SRV_KV_BLOCKS = _obs_metrics.gauge(
    "serving.kv_blocks_in_use",
    "unified-pool KV blocks currently referenced (tables + prefix store)")
_SRV_KV_BYTES = _obs_metrics.counter(
    "serving.kv_bytes_read",
    "KV bytes gathered by decode attention (table-mapped blocks only)")
_SRV_PREEMPTIONS = _obs_metrics.counter(
    "serving.preemptions",
    "running requests swapped out under KV block pressure")
_SRV_SWAP_OUT_BYTES = _obs_metrics.counter(
    "serving.kv_swap_out_bytes",
    "KV bytes moved device->host by the tiered cache (kind=\"lane\" "
    "preempted-lane chains, kind=\"demote\" evicted prefix blocks)")
_SRV_SWAP_IN_BYTES = _obs_metrics.counter(
    "serving.kv_swap_in_bytes",
    "KV bytes uploaded host->device by tiered-cache swap-ins instead "
    "of being recomputed")
_SRV_SWAP_AVERTED = _obs_metrics.counter(
    "serving.kv_swaps_averted_flops",
    "estimated prefill FLOPs swap-ins avoided (averted tokens x the "
    "program-card per-token prefill cost)")
_SRV_HOST_OCC = _obs_metrics.gauge(
    "serving.host_arena_occupancy_ratio",
    "host spill-arena blocks in use / arena capacity")
_SRV_SPEC_ACCEPT = _obs_metrics.histogram(
    "serving.spec_accept_len",
    "tokens emitted per speculative verify window (accepted prefix + 1)",
    buckets=(1, 2, 3, 4, 5, 6, 8, 12, 17))
_SRV_SPEC_DRAFTED = _obs_metrics.counter(
    "serving.spec_draft_tokens",
    "draft tokens proposed to the verify forward")
_SRV_SPEC_ACCEPTED = _obs_metrics.counter(
    "serving.spec_accepted_tokens",
    "draft tokens whose sampled verification matched")
_SRV_SPEC_RATE = _obs_metrics.gauge(
    "serving.spec_accept_rate",
    "cumulative accepted / drafted speculative tokens")
_SRV_SPEC_EMA = _obs_metrics.gauge(
    "serving.spec_lane_accept_ema",
    "per-lane speculative acceptance EMA driving the adaptive gates")
_SRV_SPEC_FORCED = _obs_metrics.counter(
    "serving.spec_forced_tokens",
    "accepted draft tokens proposed by the grammar's forced-token "
    "chains (a subset of serving.spec_accepted_tokens)")
_SRV_GRAMMAR_MASKED = _obs_metrics.histogram(
    "serving.grammar_masked_fraction",
    "fraction of the vocab masked per constrained emitted token",
    buckets=(0.5, 0.9, 0.99, 0.999, 0.9999, 1.0))
_SRV_KV_OCC = _obs_metrics.gauge(
    "serving.kv_pool_occupancy_ratio",
    "unified KV pool blocks in use / pool capacity")
_SRV_BUCKETS = _obs_metrics.gauge(
    "serving.decode_bucket_count",
    "distinct compiled decode programs ((horizon, nb, K) triples)")
_SRV_ABORTS = _obs_metrics.counter(
    "serving.requests_aborted", "requests cancelled by the caller")
_SRV_DEADLINE = _obs_metrics.counter(
    "serving.deadline_expired",
    "queued requests aborted because their admission deadline passed "
    "(a subset of serving.requests_aborted)")
_SRV_QUEUE_WAIT = _obs_metrics.histogram(
    "serving.queue_wait_seconds",
    "submit-to-admission wall seconds, observed when a request claims "
    "a slot (re-admissions after preemption observe again)")
_SRV_PREFILL_CHUNKS = _obs_metrics.histogram(
    "serving.prefill_chunks",
    "chunk dispatches per chunked-prefill request, observed when its "
    "final chunk samples the first token",
    buckets=(1, 2, 4, 8, 16, 32, 64))
_SRV_PREFILL_INTERFERE = _obs_metrics.counter(
    "serving.prefill_interference_seconds",
    "wall seconds decode horizons were delayed by interleaved prefill "
    "chunk dispatches (chunk dispatches issued while decode lanes were "
    "active)")
# compile/cache families SHARED with jit/api.py: one place answers
# "which function retraced" for both to_static and serving programs
_COMPILE_COUNT = _obs_metrics.counter(
    "jit.compile_count", "to_static trace+compile builds, by function")
_CACHE_HIT = _obs_metrics.counter(
    "jit.cache_hit", "to_static calls served from the jit cache")
_COMPILE_SECONDS = _obs_metrics.histogram(
    "jit.compile_seconds",
    "wall seconds from cache miss to first result, by function")


class CompiledFn:
    """jax.jit wrapper that counts compile-cache hits/misses by input
    signature (shape+dtype of every array leaf, plus the VALUES of any
    static args — a new static horizon bucket is a new program).  The
    miss counter is the engine's observable proof of static-shape
    serving: a multi-request run with heterogeneous prompt lengths must
    show decode misses == number of distinct horizon buckets and prefill
    misses == number of distinct length buckets.  Hits/misses also land
    on the typed registry (``jit.compile_count`` / ``jit.cache_hit``
    labeled ``fn=name``) and every miss leaves a retrace-cause event plus
    a compile begin/end pair on the timeline.

    With ``capture_cards=True`` every miss also probes the lowered
    program for a :class:`~paddle_tpu.observability.profiling
    .ProgramCard` — XLA cost/memory analysis, compile seconds, donated
    bytes, and whatever static metadata ``meta_fn(args)`` supplies
    (the engine passes the bucket key).  The probe's
    ``lowered.compile()`` may re-run XLA (the executable cache does not
    absorb it on every backend), so cards are memoized PROCESS-WIDE by
    (name, signature): a second engine with the same shapes pays
    nothing.  ``self.last_card`` tracks the card of the most recent
    dispatch (hit or miss) — the engine's per-dispatch cost model."""

    def __init__(self, fn, donate_argnums=(), name=None, static_argnums=(),
                 capture_cards=False, meta_fn=None):
        self._jit = jax.jit(fn, donate_argnums=donate_argnums,
                            static_argnums=static_argnums)
        self._name = name or getattr(fn, "__name__", "fn")
        self._static = tuple(static_argnums)
        self._donate = tuple(donate_argnums)
        self._capture_cards = bool(capture_cards)
        self._meta_fn = meta_fn
        self._seen = set()
        self.cards = {}              # signature -> ProgramCard
        self.last_card = None
        self.misses = 0
        self.hits = 0

    @property
    def calls(self):
        return self.hits + self.misses

    def _signature(self, args):
        static = tuple(args[i] for i in self._static if i < len(args))
        dynamic = [a for i, a in enumerate(args) if i not in self._static]
        return static + tuple(
            (tuple(jnp.shape(a)), str(jnp.result_type(a)))
            for a in jax.tree.leaves(dynamic))

    @staticmethod
    def _card_key(sig):
        """Short stable card key for one input signature (the human-
        readable bucket semantics live in the card's meta)."""
        import hashlib

        return hashlib.sha1(repr(sig).encode()).hexdigest()[:12]

    def _donated_bytes(self, args):
        """Bytes of the argument leaves a dispatch donates (aval
        metadata — safe to compute even around donation)."""
        total = 0
        for i in self._donate:
            if i < len(args):
                for leaf in jax.tree.leaves(args[i]):
                    total += int(np.prod(jnp.shape(leaf), dtype=np.int64)
                                 * jnp.dtype(jnp.result_type(leaf)).itemsize)
        return total

    def _meta(self, args):
        if self._meta_fn is None:
            return {}
        try:
            return dict(self._meta_fn(args))
        except Exception:            # pragma: no cover - defensive
            return {}

    def __call__(self, *args):
        sig = self._signature(args)
        if sig in self._seen:
            self.hits += 1
            _CACHE_HIT.inc(fn=self._name)
            card = self.cards.get(sig)
            if card is not None:
                card.dispatches += 1
            self.last_card = card
            return self._jit(*args)
        self._seen.add(sig)
        self.misses += 1
        _obs_events.instant(
            "jit.retrace", cat="serving", fn=self._name,
            cause=("first_call" if self.misses == 1
                   else "new_input_signature"),
            cached_signatures=len(self._seen) - 1)
        _obs_events.begin("jit.compile", cat="serving", fn=self._name)
        # lower BEFORE the call: on donating backends the call deletes
        # the donated buffers, after which tracing them would fail.  A
        # process-wide card for this exact program skips the probe.
        lowered = card = None
        donated = 0
        if self._capture_cards:
            key = self._card_key(sig)
            card = _obs_profiling.default_registry().get(self._name, key)
            if card is None:
                donated = self._donated_bytes(args)
                try:
                    lowered = self._jit.lower(*args)
                except Exception:    # pragma: no cover - defensive
                    lowered = None
        t0 = time.perf_counter()
        try:
            return self._jit(*args)
        finally:
            dt = time.perf_counter() - t0
            _COMPILE_COUNT.inc(fn=self._name)
            _COMPILE_SECONDS.observe(dt, fn=self._name)
            _obs_events.end("jit.compile", cat="serving", fn=self._name,
                            seconds=round(dt, 9))
            if self._capture_cards:
                if card is None and lowered is not None:
                    card = _obs_profiling.capture(
                        self._name, key, lowered, compile_seconds=dt,
                        donated_bytes=donated, meta=self._meta(args),
                        backend=jax.default_backend())
                if card is not None:
                    card.dispatches += 1
                    self.cards[sig] = card
                self.last_card = card


@dataclass
class EngineConfig:
    num_slots: int = 8
    max_seq_len: int = 256
    #: smallest prefill bucket; prompts pad up to the next power of two
    min_prefill_bucket: int = 8
    #: chunked prefill (Sarathi-style): split every prefill whose
    #: suffix exceeds this many tokens into fixed-size chunks dispatched
    #: one per step boundary, interleaved with decode horizons, so a
    #: long prompt can no longer monopolize the engine (TPOT spikes for
    #: the active decode batch shrink to one chunk-bucket program per
    #: boundary).  Normalized to a power of two >= min_prefill_bucket
    #: (the compile-cache discipline: every chunk dispatch reuses ONE
    #: program per lane bucket), and the per-dispatch token budget is
    #: chunk_tokens per lane.  The lane's block table grows chunk by
    #: chunk, partial progress is adopted into the prefix radix store at
    #: every chunk boundary (preemption mid-prefill resumes from the
    #: boundary via an ordinary prefix hit), and the final chunk samples
    #: the request's first token under the unchanged
    #: ``request_key(seed, counts)`` PRNG — so chunked output is
    #: BITWISE-equal to whole-prompt prefill, greedy and seeded.
    #: 0 disables (whole-prompt prefill).
    prefill_chunk_tokens: int = 0
    #: kv cache dtype; None = the model's parameter dtype
    cache_dtype: object = None
    #: largest number of fused decode steps one compiled dispatch may
    #: scan (power of two; 1 disables horizon decode).  The adaptive
    #: policy picks a bucket in [1, max_horizon] at every boundary.
    max_horizon: int = 8
    #: prefix-cache block size in tokens: full blocks of every admitted
    #: prompt are cached and reused by later prompts sharing the prefix
    #: (0 disables prefix caching)
    prefix_block_size: int = 16
    #: device-byte budget for the prefix-cache block pool; the pool
    #: holds budget // bytes_per_block blocks, LRU-evicted when full
    prefix_cache_bytes: int = 8 << 20
    #: admission reorder window: a queued request is never overtaken by
    #: more than this many later-submitted requests when admission
    #: groups same-bucket prompts into one prefill dispatch (0 = strict
    #: FIFO, co-batching only contiguous same-bucket runs)
    reorder_window: int = 8
    #: total blocks in the unified paged KV pool (incl. the reserved
    #: scratch block 0).  0 = auto: every slot can grow to a full row
    #: plus prefix-cache headroom — no request can ever starve.  A
    #: smaller explicit value oversubscribes HBM: admission defers and
    #: decode preempts the youngest lane when the pool runs dry.
    kv_pool_blocks: int = 0
    #: ragged decode attention: bucket the decode program's block-table
    #: width to a power of two of the deepest live row, so per-step KV
    #: reads track live sequence length.  False pins the width to
    #: max_blocks_per_slot — the slotted-bandwidth ablation knob
    #: (benchmarks/bench_decode.py measures both).
    ragged_attention: bool = True
    #: speculative decoding: max draft tokens per lane per fused step.
    #: 0 = plain decode.  K > 0 self-drafts K tokens per lane from its
    #: prompt+output history (prompt-lookup n-gram matching, traced into
    #: the decode program), verifies all K+1 positions in one forward,
    #: and emits the longest matching prefix plus one — greedy and
    #: seeded-sampled output stays bitwise-equal to spec_k=0.
    spec_k: int = 0
    #: shrink the dispatch draft width to 0 (plain decode) when no
    #: running lane's recent acceptance EMA clears spec_accept_floor;
    #: lanes below the floor are also gated off inside a K-wide dispatch
    #: (they draft nothing and emit exactly one token per step)
    spec_adaptive: bool = True
    #: trailing-suffix length the self-drafter matches on
    spec_ngram: int = 2
    #: per-lane acceptance-rate floor (EMA of accepted/K per verify
    #: window) below which adaptive drafting turns off for that lane
    spec_accept_floor: float = 0.125
    #: weight-only serving quantization: "int8" absmax-calibrates
    #: per-output-channel scales for every Linear projection at engine
    #: construction (quantization.quantize_for_serving) and stores int8
    #: weights + f32 scales; prefill/decode dequantize inline (fused by
    #: XLA into the matmul weight read), halving decode's weight-byte
    #: roofline.  None keeps fp weights — and the compiled programs
    #: bitwise-identical to an unquantized engine.
    weight_dtype: object = None
    #: KV-cache storage dtype for the unified paged pool: "int8" stores
    #: quantized blocks with one f32 absmax scale per token beside the
    #: block table (quantize at append/COW, dequantize after the
    #: attention gather), halving per-step serving.kv_bytes_read and
    #: ~2x-ing how many sequences fit a fixed kv_pool_blocks byte
    #: budget.  None keeps the fp pool (cache_dtype).
    kv_cache_dtype: object = None
    #: tiered KV cache: host-RAM byte budget for the spill arena under
    #: the device pool (serving/kv_host_tier.py).  LRU-evicted prefix
    #: blocks demote into it instead of dropping, preempted lanes save
    #: their whole block chain, and re-admission swaps state back in
    #: with one batched host->device upload instead of re-prefilling.
    #: int8 pools spill at their quantized density (~4x more contexts
    #: per host byte).  0 disables the tier entirely.
    kv_host_bytes: int = 0
    #: swap-vs-recompute policy: "auto" swaps when estimated upload
    #: seconds (bytes / measured host<->device bandwidth) beat the
    #: estimated re-prefill seconds (measured per-token prefill
    #: throughput over this engine's own dispatches); "always"/"never"
    #: pin the decision (the bench's crossover sweep and the parity
    #: tests use the pinned modes).
    kv_swap_policy: str = "auto"
    #: request-scoped tracing: attach a RequestTrace flight record to
    #: every request at submit, retained by a bounded FlightRecorder
    #: (all live traces + the last ``flight_recorder_capacity``
    #: finished ones) and served at /debug/requests.  Appends are O(1)
    #: per lifecycle transition, so the decode path cost is bounded
    #: (bench_decode's tracing-overhead section measures it).
    request_tracing: bool = True
    flight_recorder_capacity: int = 256
    #: program cards: capture XLA cost/memory analysis, compile seconds,
    #: donated bytes, and the bucket key at the first compile of every
    #: decode/prefill program (observability.profiling).  Cards feed the
    #: compile.* gauges, /debug/programs, per-request cost attribution,
    #: and the live roofline gauge.  The probe may cost one extra XLA
    #: compile per DISTINCT program per process (cards are memoized
    #: process-wide, so same-shape engines re-use them); False turns the
    #: observatory off entirely.
    program_cards: bool = True
    #: start a TelemetryServer (observability.server) on this port at
    #: engine construction, stopped by close().  0 binds an ephemeral
    #: port (engine.telemetry.port reports it); None disables.
    telemetry_port: int | None = None
    #: SLO objectives over step-sized rolling windows (observability
    #: .slo): per-request TTFT seconds, per-request mean TPOT seconds,
    #: and abort rate.  None disables an objective; with all three None
    #: no tracker is created and /readyz is always ready.
    slo_ttft_s: float | None = None
    slo_tpot_s: float | None = None
    slo_abort_rate: float | None = None
    #: compliance target shared by the latency objectives (e.g. 0.95 =
    #: "p95 under the threshold") and the burn-rate denominator
    slo_target: float = 0.95
    #: rolling window sizes in OBSERVATIONS (retired requests), not
    #: wall-clock — deterministic under test; unhealthy requires both
    #: windows burning above 1x budget
    slo_fast_window: int = 64
    slo_slow_window: int = 640
    #: graceful-degradation ladder: under sustained SLO burn or pool
    #: pressure the engine steps down one level per ``degrade_patience``
    #: consecutive burning steps — 1 disables speculative decoding,
    #: 2 shrinks the decode horizon to 1 (admission at every boundary),
    #: 3 sheds lowest-priority queued requests down to ``num_slots``
    #: queued — and recovers one level per ``degrade_recover_patience``
    #: consecutive calm steps (hysteresis: recovery is deliberately
    #: slower than escalation, so the ladder can't flap).  Transitions
    #: ride the event ring and the serving.degradation_level gauge.
    degrade_enabled: bool = True
    #: pool occupancy fraction that counts as block-pool pressure
    degrade_pool_ratio: float = 0.92
    degrade_patience: int = 4
    degrade_recover_patience: int = 16
    #: structured generation (grammar-constrained decoding): capacity of
    #: the token-DFA state slab, in states.  0 (default) disables the
    #: subsystem entirely — every grammar argument threads ``None``
    #: (an empty pytree) through the compiled programs, so the knobs-off
    #: decode/prefill programs are structurally the unconstrained ones.
    #: Row 0 of the slab is the accept-all sentinel state unconstrained
    #: lanes ride; request grammars are compiled to token DFAs and
    #: installed at refcounted offsets >= 1, so mixed constrained /
    #: free-text batches share one program (no per-grammar retracing).
    grammar_max_states: int = 0
    #: the tokenizer vocabulary as a sequence of token STRINGS indexed
    #: by token id (ids >= len() are unreachable fillers).  Required to
    #: compile grammars: the compiler walks every token's characters
    #: through the grammar's character DFA to build the token-level
    #: transition table and legality bitmask.
    grammar_vocab: object = None
    #: propose the grammar's forced-token chains (states with exactly
    #: one legal token — JSON skeleton punctuation) ahead of the n-gram
    #: drafter's guesses.  Forced proposals are ~100%-acceptance drafts;
    #: the PR 7 acceptance rule and EMA gating are unchanged.
    grammar_forced_drafting: bool = True
    #: host compile-cache bound: a compiled token DFA stays cached per
    #: (grammar, eos) while any live request references its slab
    #: segment (pinned — admission walks resume histories through it),
    #: plus up to this many RETIRED entries kept LRU after the last
    #: reference drops, so repeat grammars skip recompilation without
    #: the host cache growing unboundedly under a stream of unique
    #: gateway grammars (each entry holds a dense [states, vocab]
    #: int32 table).
    grammar_cache_keep: int = 8


def _unpack_mask(rows, vocab):
    """Unpack packed legality-bitmask rows to a boolean mask.

    rows [..., W32] uint32   bit ``t % 32`` of word ``t // 32`` set
                             means token ``t`` is legal
    Returns [..., vocab] bool.  A pure shift/compare — XLA fuses it
    into the ``where`` that applies the mask, so the dense [S, vocab]
    boolean form never materializes in HBM per state table."""
    bits = jnp.arange(32, dtype=jnp.uint32)
    b = (rows[..., :, None] >> bits) & jnp.uint32(1)
    flat = b.reshape(rows.shape[:-1] + (rows.shape[-1] * 32,))
    return flat[..., :vocab].astype(bool)


@dataclass
class _ChunkProgress:
    """Host ledger of one in-flight chunked prefill.  The request holds
    its slot (scheduler RUNNING, decode-INACTIVE — the horizon scan
    masks the lane like a retired one) while fixed-size chunks of its
    admission token sequence dispatch one per step boundary.  ``covered``
    tokens are already written into the lane's KV blocks; every chunk
    boundary adopts the newly completed full blocks into the prefix
    radix store, so the boundary doubles as the preemption resume point
    (re-admission finds the progress as an ordinary prefix hit)."""

    req: object
    slot: int
    lease: object
    toks: list
    covered: int              # tokens written into the lane's KV so far
    chunks: int = 0           # chunk dispatches taken (incl. admission)


class Engine:
    """Submit/step/generate over a causal-LM Layer (GPTForCausalLM /
    LlamaForCausalLM or anything with ``.model``, ``.config`` and
    ``._logits``)."""

    _instances = 0

    def __init__(self, model, config=None, register_profiler=True):
        self.model = model
        self.config = config or EngineConfig()
        model.eval()
        mc = model.config
        self._weight_dtype = self._norm_quant_knob(
            self.config.weight_dtype, "weight_dtype")
        self._kv_quant = self._norm_quant_knob(
            self.config.kv_cache_dtype, "kv_cache_dtype")
        self._state_names = list(model.state_dict().keys())
        sd = model.state_dict()
        if self._weight_dtype:
            # weight-only PTQ: matmul weights ride the jitted programs
            # as (int8, f32-scale) pairs and _run_model dequantizes them
            # inline — XLA fuses the multiply into the weight read, so
            # only int8 bytes stream from HBM per decode step
            from ..quantization import quantize_for_serving

            qmap = quantize_for_serving(model)
            self._wq_dtypes = {n: qw.dtype for n, qw in qmap.items()}
            self._state_arrays = [
                qmap[n].pair if n in qmap else sd[n]._data
                for n in self._state_names]
        else:
            self._wq_dtypes = {}
            self._state_arrays = [sd[n]._data for n in self._state_names]
        cache_dtype = (self.config.cache_dtype
                       or model.model.embed_tokens.weight._data.dtype)
        # ONE paged block pool backs every slot's table AND the prefix
        # store; the pool block size doubles as the prefix block size.
        # With kv_pool_blocks=0 the pool is sized so no request can
        # starve (full row per slot) plus prefix-budget headroom.
        self._block_size = max(1, int(self.config.prefix_block_size) or 16)
        budget = (self.config.prefix_cache_bytes
                  if self.config.prefix_block_size else 0)
        token_bytes = (mc.kv_heads * mc.head_dim
                       * (1 if self._kv_quant
                          else jnp.dtype(cache_dtype).itemsize)
                       + (4 if self._kv_quant else 0))
        bytes_per_block = (2 * len(model.model.layers) * self._block_size
                           * token_bytes)
        prefix_capacity = int(budget) // bytes_per_block
        self.cache = PagedKVCache(
            num_layers=len(model.model.layers),
            num_slots=self.config.num_slots,
            max_seq_len=self.config.max_seq_len,
            block_size=self._block_size,
            kv_heads=mc.kv_heads, head_dim=mc.head_dim,
            dtype=cache_dtype,
            num_blocks=int(self.config.kv_pool_blocks),
            extra_blocks=prefix_capacity,
            quant_dtype=self._kv_quant)
        self.pool = self.cache.pool
        self.scheduler = Scheduler(self.config.num_slots,
                                   reorder_window=self.config.reorder_window)

        # prefix KV reuse in unified-pool mode: the radix store holds
        # refcounted blocks of self.pool — hits lease blocks straight
        # into slot tables, caching is adopt() refcounting, and the
        # byte budget bounds how many pool blocks the store may pin.
        self.prefix = PrefixCache(
            num_layers=len(model.model.layers),
            block_size=self._block_size,
            kv_heads=mc.kv_heads, head_dim=mc.head_dim,
            dtype=cache_dtype, budget_bytes=budget, pool=self.pool,
            bytes_per_block=self.pool.bytes_per_block)
        self._max_blocks = self.cache.max_blocks_per_slot
        self._leases = {}            # request_id -> PrefixLease

        # tiered KV: the host-RAM spill arena under the device pool.
        # Prefix eviction demotes into it (the spill hook runs while
        # the victim's pool block is still live), preemption saves lane
        # images, and admission promotes matching host blocks back into
        # the radix store via one batched upload (_swap_in) — so the
        # swap-in path IS the ordinary prefix-hit path and inherits its
        # bitwise guarantees.
        policy = str(self.config.kv_swap_policy)
        if policy not in ("auto", "always", "never"):
            raise ValueError(
                f"unsupported kv_swap_policy {policy!r} "
                "(supported: 'auto', 'always', 'never')")
        self._swap_policy = policy
        host_budget = int(self.config.kv_host_bytes or 0)
        if host_budget < 0:
            raise ValueError(
                f"kv_host_bytes must be >= 0, got {host_budget}")
        self.host_tier = None
        if host_budget:
            self.host_tier = HostKVTier(
                num_layers=len(model.model.layers),
                block_size=self._block_size,
                kv_heads=mc.kv_heads, head_dim=mc.head_dim,
                store_dtype=np.dtype(jnp.dtype(self.pool.store_dtype)),
                budget_bytes=host_budget,
                bytes_per_block=self.pool.bytes_per_block,
                quantized=bool(self._kv_quant))
            self.prefix.spill = self._demote_block
            self.prefix.spill_batch = self._demote_blocks
        self._swap_ins = 0               # lane/prefix swap-in passes
        self._swap_outs = 0              # lane images saved at preempt
        self._swap_in_blocks = 0
        self._swap_out_blocks = 0
        self._swap_in_bytes = 0
        self._swap_out_bytes = 0         # lane-save bytes (trace-exact)
        self._demote_bytes = 0           # prefix-demotion bytes
        self._swaps_averted_tokens = 0
        self._swaps_averted_flops = 0.0
        # measured inputs the "auto" swap policy compares: per-token
        # prefill seconds over this engine's own non-compiling
        # dispatches, and per-token prefill FLOPs from program cards
        self._prefill_dispatch_s = 0.0
        self._prefill_tokens_dispatched = 0
        self._prefill_card_flops = 0.0
        self._prefill_card_tokens = 0

        # chunked prefill: normalize the chunk size to a power of two in
        # [min_prefill_bucket, max_seq_len] so every chunk dispatch hits
        # one compiled program per lane bucket (0 = whole-prompt prefill)
        ct = int(self.config.prefill_chunk_tokens or 0)
        if ct < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0, got {ct}")
        if ct:
            ct = min(self._pow2_ceil(max(ct,
                                         self.config.min_prefill_bucket)),
                     self.config.max_seq_len)
        self._chunk_tokens = ct
        self._chunking = {}          # request_id -> _ChunkProgress
        self._chunk_dispatches = 0   # compiled chunk-continuation calls
        self._chunked_requests = 0   # requests admitted chunk-wise
        self._chunk_count_total = 0  # chunk dispatches across requests
        self._prefill_interference_s = 0.0
        self._prefill_buckets = set()   # (lanes, bucket) per dispatch
        self._context_high_water = 0    # deepest prefilled position

        # host MIRRORS of the per-slot decode state.  The authoritative
        # copy lives on device between horizons (updated inside the
        # compiled scan); the mirrors exist so admission can rebuild the
        # device arrays when it dirties them, and are maintained from
        # harvested tokens alone — retirement is detected inside the
        # scan, so it never dirties the device state.
        n = self.config.num_slots
        self._tokens = np.zeros(n, np.int32)        # last token per slot
        self._pos = np.zeros(n, np.int32)           # row length per slot
        self._seeds = np.zeros(n, np.uint32)
        self._counts = np.zeros(n, np.int32)        # tokens sampled so far
        self._temps = np.zeros(n, np.float32)
        self._top_ks = np.zeros(n, np.int32)
        self._top_ps = np.ones(n, np.float32)
        self._eos_ids = np.full(n, -1, np.int32)    # -1 = no EOS token
        self._limits = np.zeros(n, np.int32)        # max_new_tokens
        self._active = np.zeros(n, bool)
        # speculative-decode state: the per-lane token history (prompt +
        # emitted tokens — the drafter's corpus; device copy rides the
        # scan carry), the per-lane acceptance EMA, and the draft gates
        # the adaptive policy feeds into the compiled program
        self._hist = np.zeros((n, self.config.max_seq_len), np.int32)
        self._spec_ema = np.ones(n, np.float32)
        self._spec_gates = np.ones(n, bool)
        self._state_dirty = True
        self._d_tokens = self._d_pos = self._d_counts = None
        self._d_active = None
        self._d_hist = None
        self._d_gates = None
        self._d_params = None
        # device copy of the live block-table prefix ([num_slots, nb]);
        # re-uploaded when the host tables dirty or nb re-buckets
        self._d_tables = None
        self._d_tables_nb = -1

        # structured generation: per-lane DFA state ids (0 = the
        # accept-all sentinel free lanes ride) mirror + the host-master
        # slab of token-DFA tables.  The state column rides the donated
        # decode carry exactly like pos/counts; the slab tables are
        # loop-invariant operands re-uploaded only when installs or
        # releases dirty them (like the block tables).
        cap = int(self.config.grammar_max_states or 0)
        if cap < 0:
            raise ValueError(
                f"grammar_max_states must be >= 0, got {cap}")
        self._structured = cap > 0
        self._grammar_slab = (GrammarSlab(cap, mc.vocab_size)
                              if self._structured else None)
        self._dfa_state = np.zeros(n, np.int32)
        self._d_dfa_state = None
        self._d_dfa_next = self._d_dfa_mask = self._d_dfa_forced = None
        self._grammar_cache = {}     # (spec key, eos id) -> TokenDFA;
                                     # pinned while slab-installed, then
                                     # LRU-bounded (grammar_cache_keep)
        self._grammar_keys = {}      # request_id -> slab segment key
        self._grammar_cache_hits = 0
        self._grammar_cache_misses = 0

        # donation buys in-place HBM pool updates on accelerators; CPU
        # would only warn that donation is unimplemented.  The scale
        # pools (args 16/17 decode, 10/11 prefill) are donated only when
        # they carry arrays — donating the fp path's None placeholders
        # is a no-op but keeping the tuples identical to the pre-quant
        # engine documents that nothing changed with the knobs off.
        donate = jax.default_backend() not in ("cpu",)
        decode_donate = (1, 2, 3, 4, 5, 14, 15)
        prefill_donate = (8, 9)
        if self._kv_quant:
            decode_donate += (16, 17)
            prefill_donate += (10, 11)
        if self._structured:
            # the per-lane DFA state (arg 20) rides the scan carry like
            # pos — donated; the slab tables (21-23) are loop-invariant
            # inputs shared by every lane and are NOT donated
            decode_donate += (20,)
        # program-card metadata: the human-readable bucket key of each
        # compiled program, read off the dispatch's own arguments
        # (decode: tables arg 13, horizon/k statics 18/19; prefill: the
        # padded ids arg 1)
        def _decode_meta(args):
            return {"horizon": int(args[18]), "k_draft": int(args[19]),
                    "nb": int(args[13].shape[1]),
                    "num_slots": int(args[13].shape[0])}

        def _prefill_meta(args):
            return {"lanes": int(args[1].shape[0]),
                    "bucket": int(args[1].shape[1])}

        cards = bool(self.config.program_cards)
        self._decode = CompiledFn(
            self._decode_fn,
            donate_argnums=decode_donate if donate else (),
            static_argnums=(18, 19), name="serving.decode",
            capture_cards=cards, meta_fn=_decode_meta)
        self._prefill = CompiledFn(self._prefill_fn,
                                   donate_argnums=(prefill_donate
                                                   if donate else ()),
                                   name="serving.prefill",
                                   capture_cards=cards,
                                   meta_fn=_prefill_meta)
        # tiered-KV swap upload: scatter n host blocks into the pool at
        # freshly allocated ids — ONE compiled call per swap-in pass,
        # n padded to a power of two (padding rows target scratch block
        # 0) so the compile cache stays bounded by log2(max chain)
        def _upload_meta(args):
            return {"blocks": int(args[4].shape[0])}

        self._upload = CompiledFn(
            self._upload_fn,
            donate_argnums=(((0, 1) + ((2, 3) if self._kv_quant else ()))
                            if donate else ()),
            name="serving.swap_upload", capture_cards=cards,
            meta_fn=_upload_meta)

        # observability
        self._decode_steps = 0
        self._decode_horizons = 0
        self._host_syncs = 0
        self._decode_harvested = 0
        self._wasted_lane_tokens = 0
        self._horizon_buckets = set()
        self._grow = 1                   # adaptive-horizon growth state
        self._decode_buckets = set()     # compiled (horizon, nb, K)
        self._spec_draft_tokens = 0
        self._spec_accepted_tokens = 0
        self._spec_windows = 0           # verify windows of drafting lanes
        self._spec_accept_hist = {}      # tokens-emitted-per-window -> n
        self._spec_forced_tokens = 0     # accepted forced-chain drafts
        self._kv_bytes_read = 0
        # engine-local cost-model totals: card FLOPs/bytes summed over
        # THIS engine's dispatches (card.dispatches is process-global
        # across engines, so it can't serve as a per-engine total).
        # Per-request attribution must reconstruct these within 1%.
        self._program_flops = 0.0
        self._program_bytes = 0.0
        self._cow_copies = 0
        self._preemptions = 0
        self._aborted = 0
        self._deadline_expired = 0
        self._tenants = {}               # tenant -> accounting dict
        self._draining = False
        # fault injection (faults.install_faults) + degradation ladder
        self.faults = None               # FaultInjector or None
        self._fault_scope = ""
        self._admit_deferred = False     # injected pool-exhaustion pass
        self._degrade_level = 0
        self._burn_streak = 0            # consecutive burning steps
        self._calm_streak = 0            # consecutive calm steps
        self._degrade_transitions = 0
        self._degrade_history = []       # last 64 transitions
        self._degrade_sheds = 0
        self._prefill_calls = 0          # compiled prefill DISPATCHES
        self._prefill_requests = 0       # requests prefilled (>= calls)
        self._prefix_hit_tokens = 0
        self._prompt_tokens = 0
        self._tokens_generated = 0
        self._busy_s = 0.0
        self._slot_busy_integral = 0.0   # sum over steps of used/num
        self._finished = 0
        self._ttft_sum = 0.0
        self._ttft_n = 0

        Engine._instances += 1
        self._profiler_name = f"serving.engine{Engine._instances}"
        # the radix store's eviction-destination counter labels by
        # engine instance like every other serving.* family
        self.prefix.metric_label = self._profiler_name
        self._finalizer = None
        if register_profiler:
            from .. import profiler as _profiler

            # the provider must NOT keep the engine alive (a bound method
            # in a process-global registry pins the engine — and its full
            # KV cache — forever): register a weakref-backed provider and
            # let GC unregister it, so repeated engine construction in
            # one process never leaks stale providers into
            # profiler.counters()
            ref = weakref.ref(self)

            def _provider():
                eng = ref()
                return eng.counters() if eng is not None else {}

            _profiler.register_counter_provider(self._profiler_name,
                                                _provider)
            self._finalizer = weakref.finalize(
                self, _profiler.unregister_counter_provider,
                self._profiler_name)

        # observability phase 3: the device-memory ledger reconciles
        # what the engine KNOWS it holds (paged KV pool, weights,
        # device decode state) against jax.live_arrays() at stats()
        # time; live bytes NOBODY accounts for growing past the first
        # snapshot is the leak signature (memory.leak_delta_bytes).
        # Engine-owned, so the accounting closures can't outlive it.
        self.ledger = _obs_memory.MemoryLedger(self._profiler_name)
        self.ledger.register("kv_pool", self._kv_pool_bytes)
        self.ledger.register("weights", self._weight_device_bytes)
        self.ledger.register("engine_state", self._state_device_bytes)
        if self.host_tier is not None:
            # host arena: accounted SEPARATELY from the device ledger
            # (numpy buffers never appear in jax.live_arrays(), so
            # folding them into the device sum would poison
            # leak_delta_bytes) — register_host keeps the reconciliation
            # exact while memory.host_arena_bytes reports the pinned
            # footprint
            self.ledger.register_host("kv_host_arena",
                                      self._host_arena_bytes)

        # observability phase 2: per-request flight records, declared
        # SLOs over the retirement stream, and the HTTP telemetry
        # endpoint.  The server holds the recorder/tracker (not the
        # engine), so it can never pin the engine's KV pool alive.
        self.recorder = (
            _obs_tracing.FlightRecorder(
                self.config.flight_recorder_capacity)
            if self.config.request_tracing else None)
        self.slo = None
        cfg = self.config
        if (cfg.slo_ttft_s is not None or cfg.slo_tpot_s is not None
                or cfg.slo_abort_rate is not None):
            from ..observability.slo import SLOTracker

            self.slo = SLOTracker(self._profiler_name)
            windows = dict(fast_window=cfg.slo_fast_window,
                           slow_window=cfg.slo_slow_window)
            if cfg.slo_ttft_s is not None:
                self.slo.declare("ttft", cfg.slo_ttft_s,
                                 target=cfg.slo_target, **windows)
            if cfg.slo_tpot_s is not None:
                self.slo.declare("tpot", cfg.slo_tpot_s,
                                 target=cfg.slo_target, **windows)
            if cfg.slo_abort_rate is not None:
                # 0/1 observations per retirement; "abort rate < Z"
                # is "1 - Z of observations must be 0"
                self.slo.declare("abort", 0.5,
                                 target=1.0 - cfg.slo_abort_rate,
                                 unit="bool", **windows)
        self.telemetry = None
        if cfg.telemetry_port is not None:
            from ..observability.server import TelemetryServer

            self.telemetry = TelemetryServer(
                port=cfg.telemetry_port, recorder=self.recorder,
                slo=self.slo).start()

    def close(self):
        """Stop the telemetry server and unregister this engine's
        counter provider (idempotent; the provider unregistration also
        runs automatically when the engine is garbage-collected)."""
        if self.telemetry is not None:
            self.telemetry.stop()
        if self._finalizer is not None:
            self._finalizer()

    def install_faults(self, injector, scope=""):
        """Arm deterministic fault injection (faults.FaultInjector) on
        this engine's ``engine.admit`` site; None disarms.  ``scope``
        names this engine in the plan (usually the worker name)."""
        self.faults = injector
        self._fault_scope = scope or self._profiler_name

    @staticmethod
    def _norm_quant_knob(value, name):
        """Normalize a serving quant knob to None or "int8"
        (case-insensitive)."""
        key = value if value is None else str(value).lower()
        if key in (None, "", "none"):
            return None
        if key in ("int8", "i8"):
            return "int8"
        raise ValueError(
            f"unsupported {name} {value!r} (supported: None, 'int8')")

    # ------------------------------------------------------------ pure fns
    def _run_model(self, state_arrays, ids, views):
        """Functionalized forward: raw param arrays + token ids + PagedKV
        views -> (last-position logits [B, vocab], new views).

        Weight-quantized entries arrive as (int8, f32-scale) pairs and
        are dequantized HERE, inside the traced program — XLA fuses
        ``q.astype(f32) * scale`` into the consuming matmul's weight
        read, so every caller (prefill, horizon scan, verify windows)
        streams int8 weight bytes without code changes of its own."""
        arrays = {}
        for name, a in zip(self._state_names, state_arrays):
            if type(a) is tuple:
                q, scale = a
                a = (q.astype(jnp.float32)
                     * scale).astype(self._wq_dtypes[name])
            arrays[name] = a
        with _tape.no_grad():
            with self.model.use_state(arrays):
                h, new_views = self.model.model(Tensor(ids), caches=views)
                logits = self.model._logits(h)
        return logits._data, new_views

    def _prefill_fn(self, state_arrays, ids, lengths, prefix_lens,
                    tables, cow_src, cow_dst, counts, pool_k, pool_v,
                    pool_ks, pool_vs, seeds, temps, top_ks, top_ps,
                    dfa_state=None, dfa_mask=None):
        """Batched fused prefill over the paged pool: one compiled
        dispatch prefills a whole admission batch.

        ids [L, bucket]      right-padded prompt SUFFIXES (the part not
                             served by the prefix cache)
        lengths [L]          suffix lengths (>= 1: an exact-hit prompt
                             still prefills its final token)
        prefix_lens [L]      cached-prefix lengths incl. a COW tail
                             match (0 on a miss)
        tables [L, MB]       each lane's block-table row: leased prefix
                             blocks first, then private blocks covering
                             the rest of the prompt.  Padding lanes are
                             all-zero — their writes land in scratch.
        cow_src/cow_dst [L]  copy-on-write: cached tail block to copy
                             into the lane's private tail block before
                             the model runs (0/0 = no-op scratch copy)
        counts [L]           tokens already sampled (0 on first
                             admission; preemption re-admission passes
                             ``n_generated - 1`` so the PRNG reproduces
                             the in-flight token bitwise)

        No gathers: cached prefix blocks are ALREADY in the lane's
        table, so attention reads them in place.  The only data motion
        is the single-block COW copy; the model then scatters suffix
        k/v at ``prefix_lens`` (overwriting the COW block from the
        divergence offset on) and the first token is sampled from the
        last valid position's logits with ``request_key(seed, count)``.

        ``pool_ks``/``pool_vs`` are the quantized pool's per-token scale
        buffers (None on the fp path — an empty pytree, so the traced
        program is unchanged when the knob is off).  The COW copy moves
        a block's scales with its bytes, keeping every stored token's
        dequantization step attached to it.

        ``dfa_state``/``dfa_mask`` are the structured-generation lane
        states ([L] slab-global row ids) and the slab legality bitmask —
        the first sampled token of a constrained lane is masked to its
        admission state's legal set.  Free and padding lanes ride row 0
        (the accept-all sentinel), whose all-ones mask makes the
        ``where`` a bitwise identity; with ``grammar_max_states=0`` both
        thread None, leaving the traced program unchanged."""
        # COW first: duplicate-dst lanes (all no-COW lanes share dst 0)
        # write identical values, so the scatter is collision-safe
        pool_k = [pk.at[cow_dst].set(pk[cow_src]) for pk in pool_k]
        pool_v = [pv.at[cow_dst].set(pv[cow_src]) for pv in pool_v]
        if pool_ks is not None:
            pool_ks = [s.at[cow_dst].set(s[cow_src]) for s in pool_ks]
            pool_vs = [s.at[cow_dst].set(s[cow_src]) for s in pool_vs]
        else:
            pool_ks = [None] * len(pool_k)
            pool_vs = [None] * len(pool_v)
        views = [PagedKV(pk, pv, tables, prefix_lens, ks, vs)
                 for pk, pv, ks, vs in zip(pool_k, pool_v,
                                           pool_ks, pool_vs)]
        logits, new_views = self._run_model(state_arrays, ids, views)
        last = jax.vmap(
            lambda lg, n: jax.lax.dynamic_index_in_dim(
                lg, n - 1, axis=0, keepdims=False))(logits, lengths)
        if dfa_mask is not None:
            allowed = _unpack_mask(dfa_mask[dfa_state], last.shape[-1])
            last = jnp.where(allowed, last, MASK_FLOOR)
        keys = jax.vmap(request_key)(seeds, counts)
        first = jax.vmap(sample_token)(last, keys, temps, top_ks, top_ps)
        return (first, [nv.k for nv in new_views],
                [nv.v for nv in new_views],
                [nv.k_scale for nv in new_views],
                [nv.v_scale for nv in new_views])

    def _decode_fn(self, state_arrays, tokens, pos, counts, active, hist,
                   gates, seeds, temps, top_ks, top_ps, eos_ids, limits,
                   tables, pool_k, pool_v, pool_ks, pool_vs, horizon,
                   k_draft, dfa_state=None, dfa_next=None,
                   dfa_mask=None, dfa_forced=None):
        """The horizon-scanned fused decode: ``lax.scan`` over ``horizon``
        fused steps, all slots, static shapes everywhere — the pool is
        the scan carry (donated on accelerators, so writes are in-place
        HBM updates) and the block tables are loop-invariant (block
        coverage for the whole horizon's write window is ensured before
        dispatch).  Retirement is detected inside the scan — a lane that
        hits its EOS id or exhausts its token budget freezes
        (``pos``/``counts`` stop advancing, its carried token stops
        changing) and harvests ``-1`` from then on.  Frozen lanes still
        run the model: their writes land at a frozen position of a
        still-held block (or in scratch once the row is zeroed), which
        the masking contract makes invisible.

        With ``k_draft > 0`` every step is a draft-and-verify window of
        ``W = k_draft + 1`` positions: the traced drafter proposes K
        continuation tokens from the lane's history buffer (``-1`` where
        it has no proposal, which no sampled token can equal), ONE
        forward scores all W positions through the paged path (the
        verify is a W-token ragged prefill against the lane's block
        table), and position j is sampled under ``fold_in(seed, cnt+j)``
        — the exact key and logits sequential decode would use for that
        token, PROVIDED the draft prefix before it matched.  The lane
        emits positions ``0..n_acc`` where ``n_acc`` is the longest
        draft prefix whose sampled verification matched, truncated at
        the first EOS/budget stop; unemitted positions harvest ``-1``.
        Rejected-position KV is garbage, but the next step writes at
        ``pos + n_emit`` onward before anything reads there, so it is
        never observed.  ``horizon`` and ``k_draft`` are static and
        ``nb = tables.shape[1]`` re-buckets by shape: one compiled
        program per (horizon, nb, K) triple.

        A quantized pool's scale buffers (``pool_ks``/``pool_vs``) ride
        the scan carry beside the pools they describe; the fp path
        carries tuples of None — empty pytrees, so the scan's jaxpr is
        unchanged with the knob off.

        Structured generation adds the per-lane DFA state ``dfa_state``
        to the carry (advanced only by EMITTED tokens, so it freezes
        with the lane) and three loop-invariant slab tables:
        ``dfa_next`` [S, V] dense transitions, ``dfa_mask`` [S, W32]
        packed legality bits, ``dfa_forced`` [S] the state's sole legal
        token or -1.  Verify-window position j is masked by the state
        reached by walking ``drafts[:j]`` through ``dfa_next``; for
        every emitted position that walk equals the true state over the
        actually-emitted tokens (the acceptance chain only survives
        position j when ``drafts[j]`` matched the mask-constrained
        sample, so the first illegal or absent draft breaks the chain
        there, and later positions — whose walked states are
        garbage-but-in-bounds rows, REJECT storing row 0 — are never
        emitted).  Masking happens before sampling inside
        ``sample_window``, so the ``fold_in(seed, count)`` key
        discipline and bitwise batched-vs-sequential parity carry over
        verbatim; free lanes ride the accept-all sentinel row 0 whose
        mask is the identity.  With ``grammar_max_states=0`` all four
        grammar arguments thread None — empty pytrees, the
        unconstrained program."""
        n, s = hist.shape
        lanes = jnp.arange(n)[:, None]
        j_idx = jnp.arange(k_draft + 1, dtype=counts.dtype)[None, :]
        if pool_ks is None:
            pool_ks = [None] * len(pool_k)
            pool_vs = [None] * len(pool_v)

        def body(carry, _):
            tok, p, cnt, act, hb, ds, pk, pv, pks, pvs = carry
            if k_draft:
                drafts = draft_tokens(hb, p + 1, k_draft,
                                      self.config.spec_ngram)
                if (dfa_next is not None
                        and self.config.grammar_forced_drafting):
                    # constraint-aware drafting: forced-token chains
                    # override the n-gram guesses BEFORE the gate mask,
                    # so the EMA gating semantics are unchanged
                    fd = forced_chain(ds, dfa_next, dfa_forced, k_draft)
                    drafts = jnp.where(fd >= 0, fd, drafts)
                drafts = jnp.where(gates[:, None], drafts, -1)
                ids = jnp.concatenate(
                    [tok[:, None], jnp.maximum(drafts, 0)], axis=1)
            else:
                ids = tok[:, None]
            views = [PagedKV(k, v, tables, p, ks, vs)
                     for k, v, ks, vs in zip(pk, pv, pks, pvs)]
            logits, new_views = self._run_model(state_arrays, ids, views)
            if dfa_mask is not None:
                sts = [ds]
                for j in range(k_draft):
                    sts.append(dfa_next[sts[-1],
                                        jnp.maximum(drafts[:, j], 0)])
                win_states = jnp.stack(sts, axis=1)
                allowed = _unpack_mask(dfa_mask[win_states],
                                       logits.shape[-1])
                e = sample_window(logits, seeds, cnt, temps, top_ks,
                                  top_ps, allowed=allowed)
            else:
                e = sample_window(logits, seeds, cnt, temps, top_ks,
                                  top_ps)
            if k_draft:
                chain = jnp.cumprod(
                    (drafts == e[:, :k_draft]).astype(jnp.int32), axis=1)
                n_acc = jnp.sum(chain, axis=1)
            else:
                n_acc = jnp.zeros_like(cnt)
            # emit the accepted prefix plus the bonus token, truncated
            # at the first position that retires the lane (EOS or
            # budget) — positions past a stop must not be emitted
            stop = (e == eos_ids[:, None]) | \
                   (cnt[:, None] + j_idx + 1 >= limits[:, None])
            keep = jnp.cumprod(1 - stop.astype(jnp.int32), axis=1)
            prev_ok = jnp.concatenate(
                [jnp.ones_like(keep[:, :1]), keep[:, :-1]], axis=1)
            emitted = (j_idx <= n_acc[:, None]) & (prev_ok > 0) \
                & act[:, None]
            n_emit = jnp.sum(emitted.astype(cnt.dtype), axis=1)
            if dfa_next is not None:
                # advance each lane's DFA by its emitted tokens only —
                # frozen lanes emit nothing and keep their state
                nds = ds
                for j in range(k_draft + 1):
                    nds = jnp.where(emitted[:, j],
                                    dfa_next[nds, e[:, j]], nds)
            else:
                nds = ds
            done = act & jnp.any(emitted & stop, axis=1)
            last = jnp.take_along_axis(
                e, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
            nxt = jnp.where(act, last, tok)
            new_cnt = cnt + n_emit       # n_emit is 0 for frozen lanes
            new_p = p + n_emit
            # append the emitted tokens to the history buffer (column S
            # is the drop target for unemitted positions)
            cols = jnp.where(emitted, p[:, None] + 1 + j_idx, s)
            hb = hb.at[lanes, cols].set(e, mode="drop")
            harvest = jnp.where(emitted, e, -1)
            return ((nxt, new_p, new_cnt, act & ~done, hb, nds,
                     tuple(v.k for v in new_views),
                     tuple(v.v for v in new_views),
                     tuple(v.k_scale for v in new_views),
                     tuple(v.v_scale for v in new_views)), harvest)

        init = (tokens, pos, counts, active, hist, dfa_state,
                tuple(pool_k), tuple(pool_v),
                tuple(pool_ks), tuple(pool_vs))
        (tok, p, cnt, act, hb, ds, pk, pv, pks, pvs), toks = jax.lax.scan(
            body, init, None, length=horizon)
        return ((tok, p, cnt, act, hb, ds), list(pk), list(pv),
                list(pks), list(pvs), toks)

    # ------------------------------------------------------------ buckets
    def _bucket(self, prompt_len):
        b = self.config.min_prefill_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.config.max_seq_len)

    def _lane_bucket(self, n):
        """Static lane count for an n-request prefill batch: the next
        power of two, capped at num_slots (so slot ids stay unique)."""
        lanes = 1
        while lanes < n:
            lanes *= 2
        return min(lanes, self.config.num_slots)

    @staticmethod
    def _admission_tokens(req):
        """The token sequence a prefill must cover for this request.
        First admission: the prompt.  Re-admission after preemption:
        prompt + all-but-the-last generated token — the last one is
        reproduced by the prefill's own sampling (count
        ``n_generated - 1`` under the fold_in PRNG), which doubles as a
        bitwise consistency check on the swap-in."""
        if req.output_ids:
            return req.prompt_ids + req.output_ids[:-1]
        return req.prompt_ids

    def _admission_bucket(self, req):
        """The prefill length bucket a request would dispatch in right
        now: its suffix past the cached prefix, padded to a power of
        two, clamped so prefix + bucket fits the slot row.  With
        chunked prefill on, the bucket is additionally capped at the
        chunk size — the admission dispatch covers only the FIRST chunk
        of a long suffix, so no compiled prefill program is ever wider
        than the chunk bucket (the whole-prompt context cap is gone).
        Used both for co-batch grouping (Scheduler.pop_batch) and for
        sizing the actual dispatch."""
        toks = self._admission_tokens(req)
        matched = self.prefix.lookup(toks)
        bucket = min(self._bucket(len(toks) - matched),
                     self.config.max_seq_len - matched)
        if self._chunk_tokens:
            bucket = min(bucket, self._chunk_tokens)
        return bucket

    def _blocks_needed(self, req):
        """Fresh pool blocks this request's admission would allocate:
        its table entries minus the full-block prefix hits it would
        lease (a COW tail match still needs its own private block).
        Under chunked prefill only the FIRST chunk's coverage is
        allocated at admission — later chunks grow the table chunk by
        chunk, with their own pool-pressure handling."""
        toks = self._admission_tokens(req)
        matched = self.prefix.lookup(toks)
        full = matched // self._block_size
        cover = len(toks)
        if self._chunk_tokens:
            cover = min(cover, matched + self._admission_bucket(req))
        return -(-cover // self._block_size) - full

    @staticmethod
    def _pow2_floor(x):
        return 1 << (int(x).bit_length() - 1)

    @staticmethod
    def _pow2_ceil(x):
        return 1 << max(0, int(x) - 1).bit_length()

    def _attn_blocks(self, h, w=1):
        """The decode program's static block-table width ``nb`` for an
        ``h``-step horizon of ``w``-position verify windows: enough
        entries to cover the deepest live row's write window (up to
        ``h*w`` new positions when every draft is accepted), bucketed to
        a power of two and clamped to ``max_blocks_per_slot``.  With
        ``ragged_attention=False`` it pins to the full width (the
        every-step-reads-everything slotted ablation).  Attention output
        is bitwise-invariant to ``nb`` (see paged_attention.py), so
        re-bucketing never perturbs a token — it only changes how many
        blocks each step reads."""
        if not self.config.ragged_attention:
            return self._max_blocks
        mx = max((int(self._pos[s]) for s in self.scheduler.running),
                 default=0)
        need = -(-(mx + h * w) // self._block_size)
        return min(self._max_blocks, max(1, self._pow2_ceil(need)))

    def _resolve_spec_k(self):
        """The draft width for the next decode dispatch.  ``spec_k`` is
        a static compile bucket (like horizon), so the adaptive choice
        is dispatch-level: drafting stays on while ANY running lane's
        acceptance EMA clears the floor — below-floor lanes are gated
        off INSIDE the K-wide program (they draft nothing and emit one
        token per step, i.e. plain decode), and once every lane is
        below the floor the dispatch itself shrinks to K=0 so the
        verify window costs nothing at all."""
        if self._degrade_level >= 1:
            return 0                 # ladder level 1+: spec decoding off
        k = max(0, int(self.config.spec_k))
        if not k or not self.config.spec_adaptive:
            return k
        if any(self._spec_gates[s] for s in self.scheduler.running):
            return k
        return 0

    def _resolve_horizon(self, requested=None):
        """Pick the horizon bucket for the next decode dispatch.

        Explicit ``requested`` is clamped to ``[1, max_horizon]`` and
        rounded down to a power of two (the static compile buckets).
        Adaptive (``requested=None``): 1 while the queue is non-empty
        (admit at every boundary) or a lane is within one step of its
        token budget; otherwise grow multiplicatively from the last
        stable horizon toward ``max_horizon``, capped by the smallest
        remaining budget so length-retirement never wastes lane steps
        (EOS remains unpredictable — mid-horizon EOS waste is measured
        by ``serving.wasted_lane_tokens``)."""
        if self._degrade_level >= 2:
            return 1                 # ladder level 2+: admit at every
                                     # boundary, shortest commit unit
        max_h = max(1, int(self.config.max_horizon))
        if requested is not None:
            return self._pow2_floor(min(max(1, int(requested)), max_h))
        if self.scheduler.queue_depth or self._chunking:
            # pending work at the boundary (queued requests, or prompts
            # mid-chunked-prefill): tightest interleave
            return 1
        rem = min(r.remaining_budget
                  for r in self.scheduler.running.values())
        return self._pow2_floor(max(1, min(max_h, self._grow, rem)))

    # ------------------------------------------------ structured decoding
    def _norm_grammar(self, grammar, sampling):
        """Validate and eagerly compile a request grammar; returns the
        ``GrammarSpec`` or None.  All failures surface HERE — at
        submit(), before anything queues — as ``GrammarError`` (for
        unsupported grammar features, naming them) or ``ValueError``
        (for engine-configuration problems)."""
        if grammar is None:
            return None
        spec = as_grammar_spec(grammar)
        if not self._structured:
            raise ValueError(
                "grammar-constrained request on an engine without "
                "structured generation (set "
                "EngineConfig.grammar_max_states > 0 and grammar_vocab)")
        if sampling.eos_token_id is None:
            raise ValueError(
                "grammar-constrained requests require "
                "sampling.eos_token_id: EOS is legal exactly in the "
                "grammar's accept states, so without one the lane "
                "could never legally stop")
        key = (spec.key, int(sampling.eos_token_id))
        if key in self._grammar_cache:
            # LRU touch: re-insertion order is eviction order for
            # retired (refcount-zero) entries in _trim_grammar_cache
            self._grammar_cache[key] = self._grammar_cache.pop(key)
            self._grammar_cache_hits += 1
        else:
            if self.config.grammar_vocab is None:
                raise ValueError(
                    "EngineConfig.grammar_vocab is required for "
                    "structured generation: the compiler walks every "
                    "vocab token's characters through the grammar")
            self._grammar_cache[key] = compile_grammar(
                spec, self.config.grammar_vocab,
                int(sampling.eos_token_id),
                vocab_size=self.model.config.vocab_size)
            self._grammar_cache_misses += 1
        return spec

    def _walk_grammar(self, dfa, tokens):
        """Advance the compiled ``TokenDFA`` through ``tokens`` from its
        start state; returns the final grammar-LOCAL state id.  The walk
        uses the cached TokenDFA, where REJECT is ``-1`` — NOT the slab,
        which stores REJECT as row 0 (the accept-all sentinel), so a
        slab walk over an illegal token would silently un-constrain the
        lane instead of surfacing it.  Raises ``ValueError`` naming the
        first illegal transition."""
        st = 0
        for i, t in enumerate(tokens):
            t = int(t)
            nxt = (int(dfa.next_state[st, t])
                   if 0 <= t < dfa.vocab_size else -1)
            if nxt < 0:
                raise ValueError(
                    f"token {t} at output position {i} is illegal "
                    f"under the request grammar (DFA state {st})")
            st = nxt
        return st

    def _dfa_admission_state(self, req):
        """The slab-global DFA state a (re-)admitted constrained lane
        samples its next token from: the grammar's start row advanced
        by every token already emitted EXCEPT the last — the prefill
        itself re-samples that one under the masked logits, the same
        bitwise boundary check the PRNG resume path performs.  Fresh
        admissions have no output yet and get the start row.

        The cache entry is pinned while the request holds its slab
        reference (see ``_trim_grammar_cache``), and an illegal token
        in the history is an invariant violation here — preempted
        lanes emitted under the mask, and cross-engine ``resume_ids``
        were validated at ``submit()``."""
        key = self._grammar_keys[req.request_id]
        try:
            st = self._walk_grammar(self._grammar_cache[key],
                                    req.output_ids[:-1])
        except ValueError as e:
            raise RuntimeError(
                f"request {req.request_id} diverged from its grammar "
                f"mid-admission — {e}") from None
        return self._grammar_slab.offset(key) + st

    def _release_grammar(self, req):
        """Drop a finished/aborted request's slab segment reference and
        park its lane back on the accept-all sentinel."""
        key = self._grammar_keys.pop(req.request_id, None)
        if key is not None:
            self._grammar_slab.release(key)
            self._trim_grammar_cache()
        if req.slot is not None:
            self._dfa_state[req.slot] = 0

    def _trim_grammar_cache(self):
        """Bound the host compile cache.  Entries whose slab segment is
        live are pinned — some request still references the grammar and
        admission walks its history through the cached TokenDFA — and
        retired entries survive as an LRU of ``grammar_cache_keep``, so
        repeat grammars stay a dict hit while a stream of unique
        gateway grammars cannot grow host memory without bound."""
        keep = max(0, int(self.config.grammar_cache_keep))
        retired = [k for k in self._grammar_cache
                   if not self._grammar_slab.installed(k)]
        for k in retired[:len(retired) - keep]:
            del self._grammar_cache[k]

    def _sync_grammar_tables(self):
        """Upload the grammar slab tables — only when an install or
        release dirtied them.  Loop-invariant within a dispatch, like
        the block tables."""
        if not self._structured or not self._grammar_slab.dirty:
            return
        self._d_dfa_next = jnp.asarray(self._grammar_slab.next)
        self._d_dfa_mask = jnp.asarray(self._grammar_slab.mask)
        self._d_dfa_forced = jnp.asarray(self._grammar_slab.forced)
        self._grammar_slab.dirty = False

    def _grammar_prefill_args(self, dfa):
        """The prefill dispatch's (dfa_state, dfa_mask) tail — Nones
        with the knob off, so the fp/unconstrained program is traced
        with empty pytrees exactly as before."""
        if not self._structured:
            return (None, None)
        self._sync_grammar_tables()
        return (jnp.asarray(dfa), self._d_dfa_mask)

    def _grammar_program_args(self):
        """The decode dispatch's grammar argument tail (dfa_state,
        dfa_next, dfa_mask, dfa_forced) for a representative program
        trace — used by the sharded engine's collective census so smoke
        traces stay in lockstep with real dispatches.  Nones when
        structured generation is off."""
        if not self._structured:
            return (None, None, None, None)
        self._sync_grammar_tables()
        return (jnp.zeros(self.config.num_slots, jnp.int32),
                self._d_dfa_next, self._d_dfa_mask, self._d_dfa_forced)

    # ------------------------------------------------------------ API
    def submit(self, prompt_ids, sampling=None, priority=0,
               deadline_s=None, tenant=None, resume_ids=None,
               grammar=None):
        """Queue one request; returns the Request handle (its
        ``output_ids`` fill in as the engine steps).

        The gateway-era admission fields are optional and inert for
        plain in-process callers: ``priority`` widens the scheduler's
        overtake budget (see ``Scheduler.overtake_cap``; a NEGATIVE
        priority is the offline batch lane — interactive traffic
        overtakes it without bound, shedding and preemption pick it
        first), ``deadline_s`` bounds queue wait — a request still
        QUEUED when the deadline passes is aborted at the next
        admission pass (``finish_reason="abort"``) — and ``tenant``
        tags the request for per-tenant accounting in
        ``stats()['tenants']``.

        ``resume_ids`` is the failover entry point: tokens this request
        already generated **on another engine** before its replica
        died.  The request queues as ``resumed`` and admission takes
        the preemption-resume path — re-prefill ``prompt + resume_ids``
        with ``counts = len(resume_ids) - 1``, so the boundary token is
        re-sampled and checked bitwise against ``resume_ids[-1]``
        (sampling is a pure function of ``fold_in(seed, n_generated)``,
        identical across replicas holding the same weights) — then
        decode continues the stream exactly where the dead replica left
        off.  Requires ``len(resume_ids) < max_new_tokens`` (a resume
        with nothing left to generate is the caller's to finish).

        ``grammar`` constrains the request's output: a regex string, a
        JSON-schema dict, or a prebuilt ``GrammarSpec``.  Validation
        and compilation happen HERE, eagerly — an unsupported grammar
        raises ``GrammarError`` (and the gateway maps it to a 400
        ``invalid_grammar``) before anything queues.  Requires
        ``grammar_max_states > 0``, ``grammar_vocab``, and a
        ``sampling.eos_token_id`` (EOS is legal exactly in the
        grammar's accept states; without it the lane could never
        legally stop).  Compiled token DFAs are cached per
        ``(grammar, eos)`` and installed into the slab refcounted, so
        repeat grammars cost a dict hit; slab exhaustion (more live
        grammar states than ``grammar_max_states``) raises
        ``RuntimeError`` here, before anything queues, and a grammar +
        ``resume_ids`` combination is refused (``ValueError``) when the
        resumed tokens don't walk the grammar legally."""
        if self._draining:
            raise RuntimeError("engine is draining; submissions refused")
        prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt_ids:
            raise ValueError("empty prompt")
        if deadline_s is not None and not float(deadline_s) > 0:
            raise ValueError(
                f"deadline_s must be > 0 or None, got {deadline_s}")
        sampling = sampling or SamplingParams()
        if len(prompt_ids) + sampling.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt_len {len(prompt_ids)} + max_new_tokens "
                f"{sampling.max_new_tokens} exceeds max_seq_len "
                f"{self.config.max_seq_len}")
        resume_ids = ([int(t) for t in resume_ids]
                      if resume_ids else None)
        if resume_ids and len(resume_ids) >= sampling.max_new_tokens:
            raise ValueError(
                f"resume_ids already holds {len(resume_ids)} tokens, "
                f">= max_new_tokens {sampling.max_new_tokens}: nothing "
                "left to generate")
        grammar = self._norm_grammar(grammar, sampling)
        key = None
        if grammar is not None:
            key = (grammar.key, int(sampling.eos_token_id))
            if resume_ids:
                # cross-engine resume under a grammar: the dead replica
                # generated these under the same mask, so any illegal
                # transition means corrupt resume data — refused HERE,
                # eagerly, not silently un-constrained at admission
                try:
                    self._walk_grammar(self._grammar_cache[key],
                                       resume_ids)
                except ValueError as e:
                    raise ValueError(
                        f"resume_ids diverged from the request "
                        f"grammar: {e}") from None
            # install BEFORE the scheduler sees the request: slab
            # exhaustion is a documented, recoverable submit() error,
            # and raising it after queueing would strand a request
            # with req.grammar set but no _grammar_keys entry — the
            # next admission pass would then KeyError the step loop
            try:
                self._grammar_slab.install(key, self._grammar_cache[key])
            except Exception:
                # the freshly compiled entry is unpinned; trim so a
                # stream of refused grammars can't grow the cache
                self._trim_grammar_cache()
                raise
        try:
            req = self.scheduler.submit(prompt_ids, sampling,
                                        priority=priority,
                                        deadline_s=deadline_s,
                                        tenant=tenant, grammar=grammar)
        except BaseException:
            if key is not None:
                self._grammar_slab.release(key)
                self._trim_grammar_cache()
            raise
        if key is not None:
            self._grammar_keys[req.request_id] = key
        if resume_ids:
            # cross-engine resume: admission re-prefills this history
            # through the preemption path (resumed => queue-head anchor
            # exemption + the bitwise boundary-token check)
            req.output_ids = list(resume_ids)
            req.resumed = True
        t = self._tenants.setdefault(
            tenant if tenant is not None else "",
            {"submitted": 0, "finished": 0, "aborted": 0,
             "tokens_generated": 0})
        t["submitted"] += 1
        if self.recorder is not None:
            req.trace = _obs_tracing.RequestTrace(
                req.request_id, engine=self._profiler_name)
            gw = {}
            if req.priority:
                gw["priority"] = req.priority
            if req.deadline_s is not None:
                gw["deadline_s"] = req.deadline_s
            if req.tenant is not None:
                gw["tenant"] = req.tenant
            if resume_ids:
                gw["resumed_tokens"] = len(resume_ids)
            if grammar is not None:
                gw["grammar"] = grammar.kind
            req.trace.add(_obs_tracing.QUEUED,
                          prompt_len=req.prompt_len,
                          max_new_tokens=sampling.max_new_tokens, **gw)
            self.recorder.attach(req.trace)
        _SRV_QUEUE.set(self.scheduler.queue_depth,
                       engine=self._profiler_name)
        return req

    def admit(self):
        """Run admission + prefill for queued requests without decoding
        (step() calls this; exposed so latency-sensitive callers and
        benchmarks can separate prefill from the decode window).

        Admission pops co-bucketed batches (same suffix bucket after
        prefix matching, bounded reorder window) and prefills each batch
        in ONE compiled dispatch — N same-bucket admissible requests
        cost 1 prefill dispatch, not N.

        Block-pool capacity gates admission: a batch whose table
        entries don't fit first reclaims unpinned prefix blocks, and if
        the pool is still short the whole batch goes back to the queue
        front (order preserved) to retry after running requests retire.
        An oversubscribed pool therefore defers admission instead of
        failing mid-prefill."""
        self._expire_deadlines()
        self._admit_deferred = False
        if self.scheduler.queue_depth:
            if self._degrade_level >= 3:
                # ladder level 3: shed lowest-priority queued requests
                # down to num_slots queued (resumed requests are never
                # shed — their tokens are already streamed)
                for req in self.scheduler.shed_victims(
                        self.cache.num_slots):
                    self._degrade_sheds += 1
                    _SRV_SHED.inc(engine=self._profiler_name)
                    self.abort(req, cause="shed")
            if self.faults is not None:
                spec = self.faults.fire(SITE_ENGINE_ADMIT,
                                        scope=self._fault_scope)
                if (spec is not None
                        and spec.kind == FAULT_POOL_EXHAUSTED):
                    # behave exactly like a dry pool: defer this whole
                    # admission pass to the next horizon boundary
                    self._admit_deferred = True
                    return
        # continuation chunks first: in-flight chunked prefills advance
        # one chunk per boundary ahead of new admissions (their blocks
        # are already partly written — finishing them frees capacity
        # soonest and keeps TTFT ordering honest)
        self._advance_chunks()
        # tiered KV: promote host-arena state for the requests this
        # admission pass could plausibly pop (the free slots plus the
        # reorder window it may look past), so their admission becomes
        # a prefix hit instead of a re-prefill
        if self.host_tier is not None and self.scheduler.queue_depth:
            window = self.cache.free_slots + self.config.reorder_window
            for req in list(self.scheduler.queue)[:window]:
                if self._swap_in(req) is None:
                    # pool dry even after reclaim — a later request's
                    # swap-in can't fare better, and pressing on would
                    # only churn (each attempt's reclaim retry eats
                    # LRU radix blocks, possibly an earlier request's
                    # freshly grafted chain).  Host state is intact:
                    # swap-in consumes nothing before its device
                    # blocks are allocated.
                    break
        # while draining, the queue can only hold `resumed` requests
        # (submit() refuses and drain() aborted the rest) — re-admitting
        # them is finishing in-flight work, so admission proceeds
        while self.cache.free_slots and self.scheduler.queue_depth:
            batch = self.scheduler.pop_batch(self.cache.free_slots,
                                             bucket_of=self._admission_bucket)
            if not batch:
                break
            need = sum(self._blocks_needed(r) for r in batch)
            short = need - self.pool.free_blocks
            while short > 0 and self.prefix.reclaim(short):
                # reclaim may have evicted unpinned blocks this very
                # batch counted as prefix hits (promoted or cached
                # chains are fair LRU victims until acquire pins them),
                # so re-derive the need against the post-reclaim radix
                # and keep reclaiming until it stabilizes — each pass
                # either closes the gap or strictly shrinks the set of
                # unpinned blocks, so this terminates
                need = sum(self._blocks_needed(r) for r in batch)
                short = need - self.pool.free_blocks
            if short > 0:
                self.scheduler.queue.extendleft(reversed(batch))
                if self.scheduler.running:
                    break            # retry after retirements free blocks
                # nothing running to wait for: admit the longest
                # queue-head prefix of the batch that fits (same bucket,
                # so it still prefills as one dispatch)
                fit, free = [], self.pool.free_blocks
                for r in batch:
                    nb = self._blocks_needed(r)
                    if nb > free:
                        break
                    free -= nb
                    fit.append(r)
                if not fit:
                    raise RuntimeError(
                        f"KV pool too small: the queue head alone needs "
                        f"{self._blocks_needed(batch[0])} blocks, pool "
                        f"has {self.pool.free_blocks} free and nothing "
                        "is running to retire (raise kv_pool_blocks or "
                        "free the prefix budget)")
                for _ in fit:
                    self.scheduler.queue.popleft()
                batch = fit
            self._prefill_batch(batch)

    _admit = admit      # pre-horizon internal name, kept for callers

    def _expire_deadlines(self):
        """Abort every still-QUEUED request whose admission deadline
        passed (the gateway's deadline enforcement point: deadlines
        bound *queue wait*, so a request that already claimed a slot
        runs to completion).  Runs at the top of every admission pass;
        expired requests finish with ``finish_reason="abort"`` and are
        counted in both ``serving.requests_aborted`` and
        ``serving.deadline_expired``."""
        expired = [r for r in self.scheduler.queue if r.deadline_expired]
        for req in expired:
            self._deadline_expired += 1
            _SRV_DEADLINE.inc(engine=self._profiler_name)
            self.abort(req, cause="deadline")

    def _prefill_batch(self, batch):
        """One compiled prefill dispatch for a co-bucketed admission
        batch: allocate slots, lease cached prefix blocks straight into
        the block tables, allocate private blocks for the rest, COW +
        suffix-prefill every lane, adopt the new blocks into the radix
        store (refcounting only), then harvest first tokens and arm the
        decode state.

        With chunked prefill on, a lane whose suffix exceeds the batch
        bucket dispatches only its FIRST chunk here; the rest of its
        prompt continues one chunk per step boundary in
        :meth:`_advance_chunks`, and its first token is sampled by the
        final chunk."""
        n = len(batch)
        bucket = max(self._admission_bucket(r) for r in batch)
        lanes = self._lane_bucket(n)
        bs = self._block_size
        entries = []
        admit_events = []            # per-request trace args, for cost
        for req in batch:
            slot = self.cache.alloc()
            was_resumed = req.resumed
            self.scheduler.start(req, slot)
            _SRV_QUEUE_WAIT.observe(req.queue_seconds,
                                    engine=self._profiler_name)
            toks = self._admission_tokens(req)
            lease = self.prefix.acquire(toks)
            self._leases[req.request_id] = lease
            req.prefix_hit_tokens = lease.matched_tokens
            start = lease.matched_tokens
            take = len(toks) - start
            if self._chunk_tokens:
                take = min(take, bucket)
            cover = start + take
            # table row: leased full-match blocks first (copy-free,
            # shared), then private blocks out to the last covered token
            # (the COW tail copy, if any, lands in the first private one)
            full = len(lease.block_ids)
            for j, bid in enumerate(lease.block_ids):
                self.cache.lease_block(slot, j, bid)
            for j in range(full, -(-cover // bs)):
                if self.cache.alloc_entry(slot, j) is None:
                    # the pre-check's reclaim (or a batch-mate's
                    # acquire) may have evicted unpinned blocks this
                    # lane's lookup counted as hits — every lease taken
                    # so far is pinned, so reclaiming here only drops
                    # blocks nobody in this batch holds yet
                    if (not self.prefix.reclaim(1)
                            or self.cache.alloc_entry(slot, j) is None):
                        raise RuntimeError(
                            "KV pool exhausted mid-admission — "
                            "admit()'s capacity pre-check diverged "
                            "from the blocks actually allocated")
            cow = None
            if lease.tail_tokens:
                cow = (lease.tail_block,
                       self.cache.tables[slot, len(lease.block_ids)])
                self._cow_copies += 1
            entries.append(dict(req=req, slot=slot, lease=lease,
                                toks=toks, start=start, take=take,
                                final=cover == len(toks), cow=cow))
            _obs_events.instant("serving.slot_alloc", cat="serving",
                                slot=slot, request=req.request_id,
                                prompt_len=req.prompt_len, bucket=bucket,
                                prefix_hit=lease.matched_tokens)
            if req.trace is not None:
                # keep the event's args dict: the prefill program card
                # isn't known until the dispatch below, so its cost
                # share is patched in afterwards
                admit_events.append(req.trace.add(
                    _obs_tracing.RESUME if (req.output_ids or was_resumed)
                    else _obs_tracing.PREFILL,
                    slot=slot, bucket=bucket,
                    prefill_tokens=len(toks),
                    prefix_hit_tokens=lease.matched_tokens))
            else:
                admit_events.append(None)
            if not req.output_ids and not was_resumed:
                # async span: a request's life overlaps other requests
                # on this thread, so it pairs by id, not by B/E nesting
                # (a preempted request's span is already open)
                _obs_events.record(
                    "serving.request", phase=_obs_events.ASYNC_BEGIN,
                    cat="serving", id=req.request_id,
                    args={"slot": slot, "prompt_len": req.prompt_len,
                          "prefix_hit_tokens": lease.matched_tokens})

        first_np, dfa = self._dispatch_prefill(entries, bucket, lanes)
        name = self._profiler_name
        self._prefill_requests += n
        _SRV_PREFILL_REQS.inc(n, engine=name)
        _SRV_PREFILL_BATCH.observe(n, engine=name)

        # cost attribution: the dispatch's program-card totals split
        # evenly over the n REAL requests (padding lanes ride free but
        # their work is part of serving these n), so per-request shares
        # sum back to the engine's _program_* totals exactly
        card = self._prefill.last_card
        if card is not None:
            for ev in admit_events:
                if ev is not None:
                    if card.flops is not None:
                        ev["flops_est"] = card.flops / n
                    if card.bytes_accessed is not None:
                        ev["bytes_est"] = card.bytes_accessed / n

        # cache the new full blocks of every admitted prompt (chunked
        # lanes: the blocks their first chunk just completed): the radix
        # store takes shared references on the slot's freshly written
        # private blocks — pure host-side refcounting, no data motion
        for e in entries:
            row = self.cache.tables[e["slot"]]
            self.prefix.adopt(e["toks"][:e["start"] + e["take"]],
                              e["lease"],
                              block_of=lambda j, row=row: row[j])

        for i, e in enumerate(entries):
            req, lease, slot = e["req"], e["lease"], e["slot"]
            hit = lease.matched_tokens
            self._prefix_hit_tokens += hit
            self._prompt_tokens += len(e["toks"])
            if hit:
                _SRV_PREFIX_HIT.inc(hit, engine=name)
            if not e["final"]:
                # chunked admission: first chunk written, no token
                # sampled yet — register the continuation ledger and
                # leave the lane decode-inactive
                cover = e["start"] + e["take"]
                self._chunked_requests += 1
                self._chunk_count_total += 1
                self._chunking[req.request_id] = _ChunkProgress(
                    req, slot, lease, e["toks"], cover, chunks=1)
                self._pos[slot] = cover
                self._active[slot] = False
                self._state_dirty = True
                self._context_high_water = max(
                    self._context_high_water, cover)
                continue
            self._finish_prefill_lane(req, slot, e["toks"],
                                      int(first_np[i]), int(dfa[i]))

    def _dispatch_prefill(self, entries, bucket, lanes):
        """Build the lane arrays for a prefill dispatch (admission
        batches and chunk continuations share this) and run the ONE
        compiled call.  Returns ``(first_np, dfa)`` — the sampled
        first-token array after the host sync, and the per-lane DFA
        admission states the dispatch ran with (callers advance the
        armed lanes' mirrors through them)."""
        # lane arrays: real requests first, then padding lanes whose
        # all-zero table rows route every write to scratch block 0
        ids = np.zeros((lanes, bucket), np.int32)
        lengths = np.ones(lanes, np.int32)
        prefix_lens = np.zeros(lanes, np.int32)
        tables = np.zeros((lanes, self._max_blocks), np.int32)
        cow_src = np.zeros(lanes, np.int32)
        cow_dst = np.zeros(lanes, np.int32)
        counts = np.zeros(lanes, np.int32)
        seeds = np.zeros(lanes, np.uint32)
        temps = np.zeros(lanes, np.float32)
        top_ks = np.zeros(lanes, np.int32)
        top_ps = np.ones(lanes, np.float32)
        # per-lane DFA admission states; 0 (accept-all sentinel) for
        # free, padding, and non-final chunk lanes (whose sampled token
        # is discarded)
        dfa = np.zeros(lanes, np.int32)
        for i, e in enumerate(entries):
            req = e["req"]
            if e["final"] and req.grammar is not None:
                dfa[i] = self._dfa_admission_state(req)
            window = e["toks"][e["start"]:e["start"] + e["take"]]
            ids[i, :len(window)] = window
            lengths[i] = len(window)
            prefix_lens[i] = e["start"]
            tables[i] = self.cache.tables[e["slot"]]
            if e["cow"] is not None:
                cow_src[i], cow_dst[i] = e["cow"]
            if e["final"]:
                counts[i] = max(0, req.n_generated - 1)
            s = req.sampling
            seeds[i] = np.uint32(s.seed)
            temps[i] = s.temperature
            top_ks[i] = s.top_k
            top_ps[i] = s.top_p

        miss0 = self._prefill.misses
        t0 = time.perf_counter()
        with _obs_span("serving.prefill_pass", cat="serving",
                       engine=self._profiler_name,
                       event_args={"batch_size": len(entries),
                                   "lanes": lanes, "bucket": bucket}):
            first, new_k, new_v, new_ks, new_vs = self._prefill(
                self._state_arrays, jnp.asarray(ids),
                jnp.asarray(lengths), jnp.asarray(prefix_lens),
                jnp.asarray(tables), jnp.asarray(cow_src),
                jnp.asarray(cow_dst), jnp.asarray(counts),
                self.pool.k, self.pool.v,
                self.pool.k_scale, self.pool.v_scale,
                jnp.asarray(seeds), jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps),
                *self._grammar_prefill_args(dfa))
        self.pool.rebind(new_k, new_v, new_ks, new_vs)
        first_np = np.asarray(first)     # the one prefill host sync
        if self._prefill.misses == miss0:
            # measured per-token prefill throughput feeding the "auto"
            # swap-vs-recompute policy (compiling dispatches excluded:
            # trace+compile seconds are not recompute cost)
            self._prefill_dispatch_s += time.perf_counter() - t0
            self._prefill_tokens_dispatched += int(
                lengths[:len(entries)].sum())
        self._prefill_calls += 1
        self._prefill_buckets.add((lanes, bucket))
        _SRV_PREFILL.inc(engine=self._profiler_name)
        card = self._prefill.last_card
        if card is not None:
            self._program_flops += card.flops or 0.0
            self._program_bytes += card.bytes_accessed or 0.0
            # per-token prefill FLOPs (over the program's padded token
            # grid) — the unit kv_swaps_averted_flops bills in
            self._prefill_card_flops += card.flops or 0.0
            self._prefill_card_tokens += lanes * bucket
        return first_np, dfa

    def _finish_prefill_lane(self, req, slot, toks, tok, dfa_i):
        """Arm one lane whose prefill just completed — whole-prompt, or
        the final chunk of a chunked one: verify/record the sampled
        first token and bring the lane's decode mirrors live."""
        name = self._profiler_name
        if req.output_ids:
            # preemption swap-in: the prefill re-sampled the token
            # that was in flight when the request was swapped out —
            # fold_in(seed, n-1) must reproduce it bitwise
            if tok != req.output_ids[-1]:
                raise RuntimeError(
                    f"preemption resume diverged for request "
                    f"{req.request_id}: re-prefill sampled {tok}, "
                    f"expected {req.output_ids[-1]}")
        else:
            self._tokens_generated += 1
            _SRV_TOKENS.inc(engine=name)
            done = req.record_token(tok)
            if req.trace is not None:
                req.trace.add(_obs_tracing.FIRST_TOKEN, token=tok,
                              ttft_s=round(req.ttft, 6))
            if done:
                self._retire(req)
                return
        s = req.sampling
        self._tokens[slot] = tok
        self._pos[slot] = len(toks)
        self._context_high_water = max(self._context_high_water,
                                       len(toks))
        # the drafter's corpus: prompt (plus regenerated tokens on a
        # preemption resume) followed by the first sampled token —
        # the tail past the valid length is never matched, but zero
        # it so a reused slot carries nothing of its previous tenant
        self._hist[slot, :len(toks)] = toks
        self._hist[slot, len(toks)] = tok
        self._hist[slot, len(toks) + 1:] = 0
        self._spec_ema[slot] = 1.0   # optimistic: draft until shown
        self._spec_gates[slot] = True  # not to pay off
        # the lane's DFA state AFTER the prefill-sampled token: the
        # admission state advanced one transition (sentinel row 0
        # self-loops, so free lanes stay at 0)
        self._dfa_state[slot] = (
            int(self._grammar_slab.next[dfa_i, tok])
            if req.grammar is not None else 0)
        self._seeds[slot] = np.uint32(s.seed)
        self._counts[slot] = req.n_generated
        self._temps[slot] = s.temperature
        self._top_ks[slot] = s.top_k
        self._top_ps[slot] = s.top_p
        self._eos_ids[slot] = -1 if s.eos_token_id is None \
            else int(s.eos_token_id)
        self._limits[slot] = s.max_new_tokens
        self._active[slot] = True
        self._state_dirty = True     # admission is the ONLY host
        # write into device-resident state; retirement is detected
        # inside the scan, so it needs no re-upload

    def _advance_chunks(self):
        """Dispatch one continuation chunk for every in-flight chunked
        prefill — called at each step boundary, BEFORE admission, so a
        decode horizon runs between consecutive chunks of the same
        prompt (the interleave policy; the per-boundary prefill budget
        is one chunk-bucket program).  Each lane's block table grows to
        cover its next chunk first (reclaiming prefix blocks, then
        preempting the lowest-priority/youngest other running request
        under pool pressure — the `_ensure_blocks` ladder); all pending
        lanes then ride ONE compiled dispatch at the chunk bucket.
        Completed full blocks are adopted into the radix store at every
        boundary, so mid-prefill preemption resumes from the chunk
        boundary as an ordinary prefix hit.  A lane's final chunk
        samples its first token and arms decode."""
        if not self._chunking:
            return
        decode_live = any(bool(self._active[s])
                          for s in self.scheduler.running)
        entries = []
        for prog in list(self._chunking.values()):
            req, slot = prog.req, prog.slot
            if self.scheduler.running.get(slot) is not req:
                continue             # preempted/aborted meanwhile
            remaining = len(prog.toks) - prog.covered
            take = min(remaining, self._chunk_tokens)
            preempted_self = False
            while not self.cache.ensure_blocks(slot,
                                               prog.covered + take):
                if self.prefix.reclaim(1):
                    continue
                victim = max(
                    (r for r in self.scheduler.running.values()
                     if r is not req),
                    key=lambda r: (-r.priority, r.request_id),
                    default=None)
                if victim is None:
                    raise RuntimeError(
                        f"KV pool exhausted: chunked prefill for "
                        f"request {req.request_id} needs blocks and "
                        "there is nothing left to reclaim or preempt "
                        "(raise kv_pool_blocks)")
                self.preempt(victim)
                if self.scheduler.running.get(slot) is not req:
                    preempted_self = True
                    break
            if preempted_self:
                continue
            entries.append(dict(req=req, slot=slot, lease=prog.lease,
                                toks=prog.toks, start=prog.covered,
                                take=take, final=take == remaining,
                                cow=None, prog=prog))
        # a later lane's pressure loop may have preempted an earlier
        # lane in `entries` — its blocks are gone, drop the entry
        entries = [e for e in entries
                   if self.scheduler.running.get(e["slot"]) is e["req"]]
        if not entries:
            return
        lanes = self._lane_bucket(len(entries))
        t0 = time.perf_counter()
        first_np, dfa = self._dispatch_prefill(entries,
                                               self._chunk_tokens, lanes)
        dt = time.perf_counter() - t0
        name = self._profiler_name
        self._chunk_dispatches += 1
        self._chunk_count_total += len(entries)
        if decode_live:
            # decode lanes were live: this boundary's horizon was
            # delayed by exactly this dispatch
            self._prefill_interference_s += dt
            _SRV_PREFILL_INTERFERE.inc(dt, engine=name)
        for i, e in enumerate(entries):
            req, lease, slot = e["req"], e["lease"], e["slot"]
            prog = e["prog"]
            cover = e["start"] + e["take"]
            row = self.cache.tables[slot]
            self.prefix.adopt(e["toks"][:cover], lease,
                              block_of=lambda j, row=row: row[j])
            prog.covered = cover
            prog.chunks += 1
            self._context_high_water = max(self._context_high_water,
                                           cover)
            _obs_events.instant("serving.prefill_chunk", cat="serving",
                                slot=slot, request=req.request_id,
                                chunk=prog.chunks, covered=cover,
                                total=len(prog.toks))
            if e["final"]:
                del self._chunking[req.request_id]
                _SRV_PREFILL_CHUNKS.observe(prog.chunks, engine=name)
                if req.trace is not None:
                    req.trace.add("prefill_chunked",
                                  chunks=prog.chunks,
                                  prefill_tokens=len(prog.toks))
                self._finish_prefill_lane(req, slot, e["toks"],
                                          int(first_np[i]), int(dfa[i]))
            else:
                self._pos[slot] = cover
                self._state_dirty = True

    def _retire(self, req):
        # release every table entry: private blocks return to the pool
        # (block-leak invariant: leased_blocks == 0 once all requests
        # retire), blocks the radix store adopted live on under its
        # references, and the zeroed row routes any still-masked lane
        # writes to scratch
        if self._structured:
            self._release_grammar(req)
        if self.host_tier is not None:
            # an unconsumed lane image is dead weight once the request
            # retires — free its pinned host blocks
            self.host_tier.drop_lane(req.request_id)
        self.cache.release_slot_blocks(req.slot)
        self.cache.free(req.slot)
        self.scheduler.finish(req)
        lease = self._leases.pop(req.request_id, None)
        if lease is not None:
            self.prefix.release(lease)   # blocks become evictable again
        self._finished += 1
        self._ttft_sum += req.ttft
        self._ttft_n += 1
        tn = self._tenants.get(req.tenant if req.tenant is not None
                               else "")
        if tn is not None:
            tn["finished"] += 1
            tn["tokens_generated"] += req.n_generated
        _SRV_REQS.inc(engine=self._profiler_name)
        _SRV_TTFT.observe(req.ttft, engine=self._profiler_name)
        _obs_events.instant("serving.slot_retire", cat="serving",
                            slot=req.slot, request=req.request_id,
                            reason=req.finish_reason,
                            n_generated=req.n_generated)
        _obs_events.record(
            "serving.request", phase=_obs_events.ASYNC_END,
            cat="serving", id=req.request_id,
            args={"reason": req.finish_reason,
                  "n_generated": req.n_generated,
                  "ttft_s": round(req.ttft, 6)})
        if req.trace is not None:
            req.trace.add(_obs_tracing.FINISH, reason=req.finish_reason,
                          n_generated=req.n_generated,
                          ttft_s=round(req.ttft, 6))
            self.recorder.finish(req.trace)
        if self.slo is not None:
            self.slo.observe("ttft", req.ttft)
            if req.n_generated > 1:
                self.slo.observe(
                    "tpot", (time.time() - req.first_token_time)
                    / (req.n_generated - 1))
            self.slo.observe("abort", 0.0)
        # the freed lane keeps its frozen state (matching the device
        # copy, which masked it inside the scan); the mirror only drops
        # the active bit — no re-upload, no parking
        self._active[req.slot] = False

    def preempt(self, req):
        """Swap a RUNNING request out: release its slot, table entries,
        and prefix lease, and requeue it at the queue front with its
        generated tokens intact.  Re-admission re-prefills prompt +
        generated-so-far and the fold_in PRNG reproduces its next token
        bitwise, so the output stream is unaffected.  Called by the
        engine under KV block pressure; also public for schedulers that
        want to swap idle sequences explicitly."""
        from .scheduler import RUNNING

        if req.status != RUNNING:
            raise ValueError(
                f"cannot preempt request {req.request_id}: {req.status}")
        slot = req.slot
        # tiered KV: save the lane's block chain into the host arena
        # BEFORE the pool references drop (the device bytes must still
        # be live to device_get); re-admission swaps it back in
        self._swap_out_lane(req, slot)
        # mid-chunked-prefill: drop the continuation ledger — the chunks
        # already adopted into the radix store survive (refcounted), so
        # re-admission resumes from the last chunk boundary as an
        # ordinary prefix hit
        self._chunking.pop(req.request_id, None)
        self.cache.release_slot_blocks(slot)
        lease = self._leases.pop(req.request_id, None)
        if lease is not None:
            self.prefix.release(lease)
        self._active[slot] = False
        # the vacated lane rides the accept-all sentinel; the request
        # KEEPS its slab segment reference (it is still live and will
        # re-admit), so its grammar tables stay installed
        self._dfa_state[slot] = 0
        self._state_dirty = True
        self.scheduler.requeue_front(req)
        self.cache.free(slot)
        self._preemptions += 1
        _SRV_PREEMPTIONS.inc(engine=self._profiler_name)
        _obs_events.instant("serving.preempt", cat="serving", slot=slot,
                            request=req.request_id,
                            n_generated=req.n_generated)
        if req.trace is not None:
            req.trace.add(_obs_tracing.PREEMPT, slot=slot,
                          n_generated=req.n_generated)

    def abort(self, req, cause=None):
        """Cancel a request: a QUEUED one leaves the queue, a RUNNING
        one releases its slot, table entries, and prefix lease (the
        preemption teardown) without requeueing.  The request finishes
        with ``finish_reason="abort"`` and keeps whatever tokens it had
        generated; aborts feed the ``abort`` SLO objective and the
        flight record ends with an ``abort`` event.  ``cause`` (e.g.
        ``"deadline"``, ``"drain"``, ``"client_disconnect"``) is
        recorded on the trace event and the process event ring; the
        caller-facing ``finish_reason`` stays ``"abort"``."""
        from .scheduler import FINISHED, FINISH_ABORT, RUNNING, WAITING

        if req.status == FINISHED:
            raise ValueError(
                f"cannot abort request {req.request_id}: already "
                f"finished ({req.finish_reason})")
        if self.host_tier is not None:
            self.host_tier.drop_lane(req.request_id)
        if req.status == WAITING:
            try:
                self.scheduler.queue.remove(req)
            except ValueError:
                raise ValueError(
                    f"cannot abort request {req.request_id}: waiting "
                    "but not queued on this engine") from None
            req.status = FINISHED
            if self._structured:
                self._release_grammar(req)
        else:
            assert req.status == RUNNING
            slot = req.slot
            self._chunking.pop(req.request_id, None)
            if self._structured:
                self._release_grammar(req)
            self.cache.release_slot_blocks(slot)
            lease = self._leases.pop(req.request_id, None)
            if lease is not None:
                self.prefix.release(lease)
            self._active[slot] = False
            self._state_dirty = True
            self.scheduler.finish(req)
            self.cache.free(slot)
        req.finish_reason = FINISH_ABORT
        self._aborted += 1
        tn = self._tenants.get(req.tenant if req.tenant is not None
                               else "")
        if tn is not None:
            tn["aborted"] += 1
            tn["tokens_generated"] += req.n_generated
        name = self._profiler_name
        _SRV_ABORTS.inc(engine=name)
        _SRV_QUEUE.set(self.scheduler.queue_depth, engine=name)
        if req.admit_time is not None:
            # only requests that prefilled opened an async span
            _obs_events.record(
                "serving.request", phase=_obs_events.ASYNC_END,
                cat="serving", id=req.request_id,
                args={"reason": FINISH_ABORT, "cause": cause,
                      "n_generated": req.n_generated})
        if req.trace is not None:
            extra = {} if cause is None else {"cause": cause}
            req.trace.add(_obs_tracing.ABORT,
                          n_generated=req.n_generated, **extra)
            self.recorder.finish(req.trace)
        if self.slo is not None:
            self.slo.observe("abort", 1.0)
        return req

    def _ensure_blocks(self, h, w=1):
        """Extend every running slot's block table to cover its next
        ``h * w`` write positions — ``w = K+1`` when drafting, so a
        fully-accepted horizon's tail-block overflow spills into table
        entries that already exist when the compiled program scatters
        through them (lazy allocation: rows only hold blocks they have
        reached).  Under pool pressure: reclaim unpinned prefix blocks
        first, then preempt the LOWEST-PRIORITY other running request
        (the offline batch lane, priority < 0, is the designated
        preemption fodder), youngest within a priority (most recently
        submitted — it has the least sunk decode work and re-prefills
        cheapest), until the allocation fits.  Runs BEFORE the step()
        harvest snapshot, so a preempted lane is never mistaken for a
        mid-horizon retirement."""
        for slot, req in sorted(self.scheduler.running.items()):
            if self.scheduler.running.get(slot) is not req:
                continue                 # preempted earlier in this loop
            if not self._active[slot]:
                continue                 # mid-chunked-prefill lane: its
                                         # table grows chunk-wise in
                                         # _advance_chunks, not by decode
            need = min(int(self._pos[slot]) + h * w,
                       self.config.max_seq_len)
            while not self.cache.ensure_blocks(slot, need):
                if self.prefix.reclaim(1):
                    continue
                victim = max(
                    (r for r in self.scheduler.running.values()
                     if r is not req),
                    key=lambda r: (-r.priority, r.request_id),
                    default=None)
                if victim is None:
                    raise RuntimeError(
                        f"KV pool exhausted: slot {slot} needs blocks "
                        "for its decode window and there is nothing "
                        "left to reclaim or preempt (raise "
                        "kv_pool_blocks)")
                self.preempt(victim)

    # ---------------------------------------------------------- tiered KV
    def _upload_fn(self, pool_k, pool_v, pool_ks, pool_vs, ids,
                   kd, vd, ksd, vsd):
        """Swap-in upload program: scatter ``n`` whole host blocks into
        the pool arrays at freshly allocated ``ids``.  ``kd``/``vd``
        are ``[n, num_layers, block_size, kv_heads, head_dim]`` at the
        pool's storage dtype; scale planes ride beside them on
        quantized pools (``None`` placeholders otherwise, keeping the
        fp program structurally scale-free).  Pure byte movement — the
        uploaded bytes ARE the bytes the pool once held, which is what
        makes a swap-in bitwise-indistinguishable from recompute."""
        new_k, new_v, new_ks, new_vs = [], [], [], []
        for l in range(len(pool_k)):
            new_k.append(pool_k[l].at[ids].set(kd[:, l]))
            new_v.append(pool_v[l].at[ids].set(vd[:, l]))
            if ksd is not None:
                new_ks.append(pool_ks[l].at[ids].set(ksd[:, l]))
                new_vs.append(pool_vs[l].at[ids].set(vsd[:, l]))
        if ksd is None:
            new_ks, new_vs = pool_ks, pool_vs
        return new_k, new_v, new_ks, new_vs

    def _place_pool(self):
        """Re-place the pool arrays after a swap upload rebinds them.
        No-op here; MeshEngine overrides to restore the head-sharded
        placement before the next dispatch."""

    def _host_arena_bytes(self):
        """Pinned host-RAM footprint of the spill arena (the ledger's
        host-component accounting: the payload arrays are allocated in
        full at construction, so this is constant while the tier
        lives)."""
        t = self.host_tier
        if t is None:
            return 0
        total = t.k.nbytes + t.v.nbytes
        if t.quantized:
            total += t.k_scale.nbytes + t.v_scale.nbytes
        return total

    def _fetch_blocks(self, bids):
        """Host copies of device pool blocks: ``[n, L, bs, kvh, hd]``
        k/v plus ``[n, L, bs]`` scale planes (None on fp pools).  One
        gather + device_get per layer array; on a sharded pool the get
        assembles the full block across shards (pure byte movement —
        re-sharding on the way back up is the upload's problem)."""
        idx = jnp.asarray(np.asarray(bids, np.int32))
        L = len(self.pool.k)
        k = np.stack([np.asarray(jax.device_get(self.pool.k[l][idx]))
                      for l in range(L)], axis=1)
        v = np.stack([np.asarray(jax.device_get(self.pool.v[l][idx]))
                      for l in range(L)], axis=1)
        if not self._kv_quant:
            return k, v, None, None
        ks = np.stack(
            [np.asarray(jax.device_get(self.pool.k_scale[l][idx]))
             for l in range(L)], axis=1)
        vs = np.stack(
            [np.asarray(jax.device_get(self.pool.v_scale[l][idx]))
             for l in range(L)], axis=1)
        return k, v, ks, vs

    def _demote_block(self, path, block_id):
        """``PrefixCache.spill`` hook: device_get one evicted radix
        block into the host arena (called BEFORE the pool block is
        released, while its bytes are still live).  True means the
        arena kept it — the eviction is a demotion, not a loss."""
        return self._demote_blocks([path], [block_id])[0]

    def _demote_blocks(self, paths, bids):
        """``PrefixCache.spill_batch`` hook: demote a whole eviction
        pass's victims with ONE batched gather + device_get (called
        BEFORE the pool blocks are released, while their bytes are
        still live).  Bulk reclaims — admission evicting many blocks
        to fit a batch — would otherwise serialize one synchronous
        device round-trip per victim on the admission hot path; this
        bounds the copy cost per reclaim pass instead.  One bool per
        block: True means the arena kept it."""
        tier = self.host_tier
        if tier is None or not tier.capacity:
            return [False] * len(bids)
        k, v, ks, vs = self._fetch_blocks(bids)
        name = self._profiler_name
        nbytes = self.pool.bytes_per_block
        out = []
        for i, path in enumerate(paths):
            ok = tier.store_prefix(path, k[i], v[i],
                                   None if ks is None else ks[i],
                                   None if vs is None else vs[i])
            if ok:
                self._demote_bytes += nbytes
                _SRV_SWAP_OUT_BYTES.inc(nbytes, engine=name,
                                        kind="demote")
            out.append(ok)
        return out

    def _swap_worthwhile(self, n_blocks, n_tokens):
        """The swap-vs-recompute crossover model: estimated upload
        seconds (bytes / measured host<->device bandwidth) vs estimated
        re-prefill seconds (this engine's own measured per-token
        prefill throughput).  ``always``/``never`` pin the decision;
        ``auto`` with no throughput sample yet swaps optimistically
        (the first measurement lands with the first prefill)."""
        if self._swap_policy == "always":
            return True
        if self._swap_policy == "never":
            return False
        if n_blocks <= 0 or n_tokens <= 0:
            return False
        if not self._prefill_tokens_dispatched:
            return True
        recompute_s = (self._prefill_dispatch_s
                       / self._prefill_tokens_dispatched) * n_tokens
        bw = _obs_memory.host_device_bandwidth_gbs(jax.default_backend())
        upload_s = n_blocks * self.pool.bytes_per_block / (bw * 1e9)
        return upload_s < recompute_s

    def _swap_out_lane(self, req, slot):
        """Tiered KV at preempt: save the lane's whole block chain into
        the host arena BEFORE its pool blocks are released, so
        re-admission can swap it back in instead of re-prefilling.
        Skipped for mid-chunked-prefill lanes (their completed chunks
        already live in the radix store and resume as a prefix hit) and
        when the policy prefers recompute; ``save_lane`` failing (arena
        full of pinned images) silently falls back to recompute."""
        tier = self.host_tier
        if tier is None or not tier.capacity:
            return False
        if req.request_id in self._chunking or not self._active[slot]:
            return False
        pos = int(self._pos[slot])
        bs = self._block_size
        nb = -(-pos // bs)
        if pos <= 0 or not self._swap_worthwhile(nb, pos):
            return False
        row = self.cache.tables[slot]
        bids = [int(row[j]) for j in range(nb)]
        if any(b == 0 for b in bids):
            return False             # defensive: chain has a hole
        k, v, ks, vs = self._fetch_blocks(bids)
        blocks = [(k[i], v[i],
                   None if ks is None else ks[i],
                   None if vs is None else vs[i]) for i in range(nb)]
        if not tier.save_lane(req.request_id, pos, blocks):
            return False
        nbytes = nb * self.pool.bytes_per_block
        self._swap_outs += 1
        self._swap_out_blocks += nb
        self._swap_out_bytes += nbytes
        name = self._profiler_name
        _SRV_SWAP_OUT_BYTES.inc(nbytes, engine=name, kind="lane")
        _obs_events.instant("serving.swap_out", cat="serving",
                            slot=slot, request=req.request_id,
                            blocks=nb, bytes=nbytes, n_tokens=pos)
        if req.trace is not None:
            req.trace.add(_obs_tracing.SWAP_OUT, blocks=nb,
                          bytes=nbytes, n_tokens=pos)
        return True

    def _swap_in(self, req):
        """Promote a QUEUED request's host-arena KV into the device
        radix store so the coming admission serves it as an ordinary
        prefix hit — no new prefill plumbing, and sharded parity is
        automatic because promotion is pure byte movement feeding the
        already-parity-gated prefill path.

        A lane image (preempt swap-out) restores the full chain
        including the partial tail block, grafted under its SHORT token
        key that only copy-on-write matching can hit — so the resume
        prefill still computes >= 1 suffix token and the engine's
        bitwise resume-divergence check stays the parity gate.  Without
        an image, demoted prefix blocks extending the device-side radix
        match are promoted instead.  Any failure (policy says
        recompute, pool dry, graft refused) degrades to recompute —
        never an error.  Returns True on a landed swap-in, None when
        the pool was dry (the admission promotion loop stops on that —
        no host state is consumed before device blocks are secured),
        False otherwise."""
        tier = self.host_tier
        if tier is None:
            return False
        toks = self._admission_tokens(req)
        bs = self._block_size
        before = self.prefix.lookup(toks)
        chain = self.prefix._walk(toks, len(toks) - 1)
        have = len(chain)
        # pin the matched parent chain for the duration of the swap-in:
        # the pool.alloc() reclaim fallback below evicts LRU unpinned
        # radix blocks, and eating this request's own parents would
        # break every graft ("promotions must land in path order") —
        # under pool pressure that turns swap-in into pure churn
        for n in chain:
            n.refcount += 1
        try:
            return self._swap_in_pinned(req, tier, toks, bs, before,
                                        have)
        finally:
            for n in chain:
                if n.refcount > 0:
                    n.refcount -= 1

    def _swap_in_pinned(self, req, tier, toks, bs, before, have):
        img = tier.peek_lane(req.request_id)
        lane = img is not None and img.n_tokens == len(toks)
        if img is not None and not lane:
            tier.drop_lane(req.request_id)   # stale: tokens moved on
        paths = []
        if lane:
            nb_chain = -(-len(toks) // bs)
            idxs = list(range(have, nb_chain))
            if not idxs or not self._swap_worthwhile(
                    len(idxs), len(toks) - have * bs):
                return False
        else:
            paths = tier.match_prefix(toks, have)
            if not paths or not self._swap_worthwhile(
                    len(paths), len(paths) * bs):
                return False
            idxs = [have + j for j in range(len(paths))]
        # allocate the device blocks BEFORE consuming any host state:
        # a dry pool then leaves the lane image / demoted entries
        # intact for the next admission pass (under a preemption storm
        # the first attempts routinely race a full pool — consuming
        # first would destroy the saved KV and force recompute forever).
        # The matched arena entries are pinned across the loop: the
        # reclaim(1) fallback fires the spill hook, and store_prefix
        # making room for a NEW demotion must not LRU-evict the entries
        # this swap-in is about to pop (device-pool-dry + arena-full is
        # exactly the pressure regime the tier serves).
        tier.pin_prefix(paths)
        try:
            dev_ids = []
            for _ in idxs:
                bid = self.pool.alloc()
                if bid is None and self.prefix.reclaim(1):
                    bid = self.pool.alloc()
                if bid is None:
                    for b in dev_ids:
                        self.pool.release(b)
                    # pool dry: recompute covers it.  None (vs False)
                    # tells the admission promotion loop to stop trying
                    # — no host state was consumed, so the next
                    # boundary retries.
                    return None
                dev_ids.append(bid)
            if lane:
                img = tier.take_lane(req.request_id)
                plan = [(i, img.hbs[i]) for i in idxs]
                consumed = list(img.hbs)
            else:
                # defense in depth: should an entry be gone anyway,
                # stop at the break (later blocks could not graft
                # without their parent), return the unused device
                # blocks, and leave the unconsumed entries resident
                plan = []
                for i, p in zip(idxs, paths):
                    hb = tier.pop_prefix(p)
                    if hb is None:
                        break
                    plan.append((i, hb))
                for b in dev_ids[len(plan):]:
                    self.pool.release(b)
                dev_ids = dev_ids[:len(plan)]
                if not plan:
                    return False
                consumed = [hb for _, hb in plan]
        finally:
            tier.unpin_prefix(paths)
        n = len(plan)
        kd = np.empty((n,) + tier.k.shape[1:], tier.k.dtype)
        vd = np.empty_like(kd)
        ksd = vsd = None
        if tier.quantized:
            ksd = np.empty((n,) + tier.k_scale.shape[1:], np.float32)
            vsd = np.empty_like(ksd)
        for j, (_, hb) in enumerate(plan):
            bk, bv, bks, bvs = tier.read_block(hb)
            kd[j], vd[j] = bk, bv
            if ksd is not None:
                ksd[j], vsd[j] = bks, bvs
        for hb in consumed:
            tier.release(hb)
        # pad to a power of two so the compile cache stays bounded;
        # padding rows scatter zeros into scratch block 0, whose
        # content is meaningless by design
        lanes = self._pow2_ceil(n)
        ids = np.zeros(lanes, np.int32)
        ids[:n] = dev_ids
        if lanes > n:
            pad = lanes - n
            kd = np.concatenate(
                [kd, np.zeros((pad,) + kd.shape[1:], kd.dtype)])
            vd = np.concatenate(
                [vd, np.zeros((pad,) + vd.shape[1:], vd.dtype)])
            if ksd is not None:
                ksd = np.concatenate(
                    [ksd, np.zeros((pad,) + ksd.shape[1:], np.float32)])
                vsd = np.concatenate(
                    [vsd, np.zeros((pad,) + vsd.shape[1:], np.float32)])
        new_k, new_v, new_ks, new_vs = self._upload(
            self.pool.k, self.pool.v,
            self.pool.k_scale, self.pool.v_scale,
            jnp.asarray(ids), jnp.asarray(kd), jnp.asarray(vd),
            None if ksd is None else jnp.asarray(ksd),
            None if vsd is None else jnp.asarray(vsd))
        self.pool.rebind(new_k, new_v, new_ks, new_vs)
        self._place_pool()
        grafted = 0
        for (idx, _), bid in zip(plan, dev_ids):
            if self.prefix.graft(toks, idx, bid):
                grafted += 1
            else:
                self.pool.release(bid)   # chain broke: recompute covers
        if not grafted:
            return False
        averted = max(0, self.prefix.lookup(toks) - before)
        nbytes = n * self.pool.bytes_per_block
        name = self._profiler_name
        self._swap_ins += 1
        self._swap_in_blocks += n
        self._swap_in_bytes += nbytes
        _SRV_SWAP_IN_BYTES.inc(nbytes, engine=name)
        self._swaps_averted_tokens += averted
        if self._prefill_card_tokens:
            fl = averted * (self._prefill_card_flops
                            / self._prefill_card_tokens)
            self._swaps_averted_flops += fl
            _SRV_SWAP_AVERTED.inc(fl, engine=name)
        _obs_events.instant("serving.swap_in", cat="serving",
                            request=req.request_id, blocks=n,
                            bytes=nbytes, averted_tokens=averted,
                            source="lane" if lane else "prefix")
        if req.trace is not None:
            req.trace.add(_obs_tracing.SWAP_IN, blocks=n, bytes=nbytes,
                          averted_tokens=averted,
                          source="lane" if lane else "prefix")
        return True

    def _sync_device_state(self):
        """Upload the per-slot state mirrors — only when admission
        dirtied them.  In steady-state decode the device arrays returned
        by the previous horizon are passed straight back in."""
        if not self._state_dirty:
            return
        self._d_tokens = jnp.asarray(self._tokens)
        self._d_pos = jnp.asarray(self._pos)
        self._d_counts = jnp.asarray(self._counts)
        self._d_active = jnp.asarray(self._active)
        self._d_hist = jnp.asarray(self._hist)
        self._d_gates = jnp.asarray(self._spec_gates)
        self._d_params = tuple(
            jnp.asarray(a) for a in (self._seeds, self._temps,
                                     self._top_ks, self._top_ps,
                                     self._eos_ids, self._limits))
        if self._structured:
            self._d_dfa_state = jnp.asarray(self._dfa_state)
        self._state_dirty = False

    def _sync_tables(self, nb):
        """Upload the live ``[:, :nb]`` prefix of the host block tables
        — only when a table changed (lease/alloc/release) or ``nb``
        re-bucketed.  In steady-state decode nothing is uploaded and the
        tables stay loop-invariant across horizons."""
        if self.cache.tables_dirty or nb != self._d_tables_nb:
            self._d_tables = jnp.asarray(self.cache.tables[:, :nb])
            self._d_tables_nb = nb
            self.cache.tables_dirty = False

    def _dispatch_horizon(self, h, k=None):
        """One compiled decode dispatch over ``h`` fused steps of
        ``k+1``-position verify windows; adopts the returned device
        state and returns the harvested ``[h, n, k+1]`` token array
        AFTER the one blocking host sync.  The block-table width ``nb``
        is bucketed per dispatch (ragged attention), and the decode
        program re-compiles only on a new (h, nb, k) triple."""
        if k is None:
            k = self._resolve_spec_k()
        self._ensure_blocks(h, k + 1)   # idempotent; step() already ran it
        nb = self._attn_blocks(h, k + 1)
        self._sync_device_state()
        self._sync_tables(nb)
        self._sync_grammar_tables()
        seeds, temps, top_ks, top_ps, eos_ids, limits = self._d_params
        misses0 = self._decode.misses
        t_disp = time.perf_counter()
        (tok, p, cnt, act, hb, nds), new_k, new_v, new_ks, new_vs, \
            toks = self._decode(
                self._state_arrays, self._d_tokens, self._d_pos,
                self._d_counts, self._d_active, self._d_hist,
                self._d_gates, seeds, temps, top_ks, top_ps, eos_ids,
                limits, self._d_tables, self.pool.k, self.pool.v,
                self.pool.k_scale, self.pool.v_scale, h, k,
                self._d_dfa_state, self._d_dfa_next, self._d_dfa_mask,
                self._d_dfa_forced)
        self.pool.rebind(new_k, new_v, new_ks, new_vs)
        self._d_tokens, self._d_pos = tok, p
        self._d_counts, self._d_active = cnt, act
        self._d_hist = hb
        self._d_dfa_state = nds
        self._decode_buckets.add((h, nb, k))
        # KV traffic actually gathered by the fallback scan (and the
        # upper bound for the block-culling Pallas kernel): every lane
        # reads its nb table-mapped blocks — k + v, all layers — per
        # step.  bytes_per_block is the pool's ACTUAL footprint: int8
        # payload + per-token f32 scales when quantized, so the quant
        # ablation's bandwidth numbers come from this same telemetry.
        step_bytes = self.cache.num_slots * nb * self.pool.bytes_per_block
        self._kv_bytes_read += step_bytes * h
        _SRV_KV_BYTES.inc(step_bytes * h, engine=self._profiler_name)
        toks = np.asarray(toks)      # the ONE host sync per horizon
        self._host_syncs += 1
        dt_disp = time.perf_counter() - t_disp
        card = self._decode.last_card
        if card is not None:
            self._program_flops += card.flops or 0.0
            self._program_bytes += card.bytes_accessed or 0.0
            # online roofline: this dispatch's bytes-accessed over its
            # wall time vs the backend bandwidth — skipped on compiling
            # dispatches, whose wall time is dominated by XLA
            if self._decode.misses == misses0:
                _obs_memory.publish_roofline(
                    self._profiler_name, h, card.bytes_accessed,
                    dt_disp, jax.default_backend())
        return toks

    def step(self, horizon=None):
        """One engine iteration: admit queued requests into free slots
        (prefill), then run ONE compiled horizon of fused decode steps
        over every slot.  ``horizon=None`` lets the adaptive policy pick
        the bucket; an explicit value is bucketed to a power of two
        (scanning past a request's retirement is correct — masked — just
        wasteful).  Returns the requests that finished during this
        step."""
        t0 = time.time()
        finished = []
        self._update_degradation()
        self.admit()
        # mid-chunked-prefill lanes are RUNNING but decode-inactive —
        # they hold a slot and blocks but emit nothing until their final
        # chunk arms them, so the decode snapshot excludes them (their
        # masked -1 rows must never reach the harvest walk)
        if any(self._active[s] for s in self.scheduler.running):
            h = self._resolve_horizon(horizon)
            k = self._resolve_spec_k()
            # block coverage (and any pressure preemption) BEFORE the
            # harvest snapshot: a lane preempted here simply isn't in
            # `active`, so its -1 harvest rows are never misread
            self._ensure_blocks(h, k + 1)
        active = {s: r for s, r in self.scheduler.running.items()
                  if self._active[s]}
        if active:
            self._horizon_buckets.add(h)
            with _obs_span("serving.decode_step", cat="serving",
                           engine=self._profiler_name,
                           event_args={"horizon": h, "spec_k": k}) as sp:
                toks = self._dispatch_horizon(h, k)
                harvested, wasted = self._harvest(toks, active, h, k,
                                                  finished)
                sp.event_args["tokens_harvested"] = harvested
            self._decode_steps += h
            self._decode_horizons += 1
            self._slot_busy_integral += h * len(active) / self.cache.num_slots
            name = self._profiler_name
            _SRV_DECODE_STEPS.inc(h, engine=name)
            _SRV_HORIZON.observe(h, engine=name)
            _SRV_TOKENS.inc(harvested, engine=name)
            if wasted:
                _SRV_WASTED.inc(wasted, engine=name)
            # adaptive growth: stable horizon (nothing retired, nothing
            # waiting) doubles the next one; churn resets to 1
            if finished or self.scheduler.queue_depth:
                self._grow = 1
            else:
                self._grow = min(max(1, int(self.config.max_horizon)),
                                 max(self._grow, h) * 2)
        dt = time.time() - t0
        self._busy_s += dt
        _SRV_STEP.observe(dt, engine=self._profiler_name)
        self._publish_gauges()
        return finished

    def _harvest(self, toks, active, h, k_draft, finished):
        """Walk the ``[h, num_slots, k_draft+1]`` harvested token
        windows, replaying each running request's stream in order:
        record the 1..K+1 emitted tokens of every live window (the
        ``-1`` tail of a window marks rejected/unemitted positions),
        retire on EOS/limit (the host check mirrors the in-scan mask),
        count post-retirement lane STEPS as waste (one per scan step,
        matching the K=0 meaning), and keep the host mirrors — last
        token, row length, sample count, token history — equal to the
        frozen device state.  Drafting lanes also update their
        acceptance EMA here, which drives the adaptive gates (a gate
        flip dirties the device state for the next upload)."""
        harvested = wasted = 0
        w = k_draft + 1
        # cost attribution: the dispatch's program-card totals split
        # evenly over the active lanes (every active lane — including
        # one that retires mid-horizon — rides the whole compiled scan),
        # so lane shares sum back to the engine's _program_* totals
        card = self._decode.last_card
        flops_share = bytes_share = None
        if card is not None and active:
            if card.flops is not None:
                flops_share = card.flops / len(active)
            if card.bytes_accessed is not None:
                bytes_share = card.bytes_accessed / len(active)
        drafted = accepted = 0
        forced_total = 0
        slab = self._grammar_slab
        vocab = int(self.model.config.vocab_size)
        floor = float(self.config.spec_accept_floor)
        gated = self._spec_gates.copy()  # gates the dispatch ran with
        for slot, req in active.items():
            done = False
            lane_tokens = lane_accept = lane_forced = 0
            # replay the lane's DFA walk on the host mirror: the same
            # slab tables the device walked, advanced by the same
            # emitted tokens, so the mirror state stays equal to the
            # (frozen) device carry — and yields per-token telemetry
            # (masked fraction, forced-draft hits) with no extra
            # device outputs
            st = int(self._dfa_state[slot]) if self._structured else 0
            constrained = st != 0
            fd_on = (constrained and k_draft
                     and bool(self.config.grammar_forced_drafting))
            for step_i in range(h):
                row = toks[step_i, slot]
                if done:
                    wasted += 1
                    continue
                if int(row[0]) < 0:
                    raise RuntimeError(
                        f"horizon mask retired slot {slot} at step "
                        f"{step_i} but the scheduler still runs its "
                        "request — in-scan EOS/limit logic diverged "
                        "from record_token")
                n_emit = 0
                # a forced-chain draft counts only while the window's
                # chain from its START state held: the device proposed
                # forced[st] at position j iff every earlier position
                # was forced too (forced_chain breaks at the first
                # non-forced state)
                win_chain = fd_on and bool(gated[slot])
                for j in range(w):
                    t = int(row[j])
                    if t < 0:
                        break            # rejected/unemitted window tail
                    n_emit += 1
                    harvested += 1
                    self._tokens_generated += 1
                    self._tokens[slot] = t
                    self._pos[slot] += 1
                    self._hist[slot, self._pos[slot]] = t
                    if constrained:
                        _SRV_GRAMMAR_MASKED.observe(
                            1.0 - float(slab.popcount[st]) / vocab,
                            engine=self._profiler_name)
                        if (win_chain and j < k_draft
                                and int(slab.forced[st]) == t):
                            lane_forced += 1
                        else:
                            win_chain = False
                        st = int(slab.next[st, t])
                    if req.record_token(t):
                        done = True      # retire AFTER the lane's trace
                        break            # event, below
                lane_tokens += n_emit
                self._counts[slot] = req.n_generated
                if k_draft and gated[slot]:
                    drafted += k_draft
                    accepted += n_emit - 1
                    lane_accept += n_emit - 1
                    self._spec_windows += 1
                    self._spec_accept_hist[n_emit] = \
                        self._spec_accept_hist.get(n_emit, 0) + 1
                    _SRV_SPEC_ACCEPT.observe(
                        n_emit, engine=self._profiler_name)
                    ema = 0.5 * float(self._spec_ema[slot]) \
                        + 0.5 * (n_emit - 1) / k_draft
                    self._spec_ema[slot] = ema
                    if self.config.spec_adaptive and \
                            (ema >= floor) != bool(self._spec_gates[slot]):
                        self._spec_gates[slot] = ema >= floor
                        self._state_dirty = True
            if constrained:
                self._dfa_state[slot] = st
                forced_total += lane_forced
            if req.trace is not None and lane_tokens:
                extra = {"forced": lane_forced} if constrained else {}
                ev = req.trace.add(_obs_tracing.DECODE, horizon=h,
                                   spec_k=k_draft, tokens=lane_tokens,
                                   accepted=lane_accept, **extra)
                if flops_share is not None:
                    ev["flops_est"] = flops_share
                if bytes_share is not None:
                    ev["bytes_est"] = bytes_share
            if done:
                self._retire(req)
                finished.append(req)
        if forced_total:
            self._spec_forced_tokens += forced_total
            _SRV_SPEC_FORCED.inc(forced_total,
                                 engine=self._profiler_name)
        if drafted:
            self._spec_draft_tokens += drafted
            self._spec_accepted_tokens += accepted
            name = self._profiler_name
            _SRV_SPEC_DRAFTED.inc(drafted, engine=name)
            _SRV_SPEC_ACCEPTED.inc(accepted, engine=name)
            _SRV_SPEC_RATE.set(
                self._spec_accepted_tokens / self._spec_draft_tokens,
                engine=name)
        self._decode_harvested += harvested
        self._wasted_lane_tokens += wasted
        return harvested, wasted

    # ------------------------------------------------- degradation ladder
    def _degrade_signal(self):
        """The pressure signal driving the ladder: the reason string
        while the engine is burning (any SLO objective unhealthy, or
        pool occupancy at/above ``degrade_pool_ratio``), else None."""
        if self.slo is not None and not self.slo.healthy:
            return "slo_burn"
        if (self.pool.blocks_in_use / self.pool.capacity
                >= float(self.config.degrade_pool_ratio)):
            return "pool_pressure"
        return None

    def _update_degradation(self):
        """One ladder tick (called every step): ``degrade_patience``
        consecutive burning steps escalate one level,
        ``degrade_recover_patience`` consecutive calm steps step back
        down one level — asymmetric on purpose (hysteresis), so a
        marginal signal can't flap the ladder."""
        if not self.config.degrade_enabled:
            return
        reason = self._degrade_signal()
        if reason is not None:
            self._calm_streak = 0
            self._burn_streak += 1
            if (self._degrade_level < len(DEGRADE_LEVELS) - 1
                    and self._burn_streak
                    >= int(self.config.degrade_patience)):
                self._set_degrade_level(self._degrade_level + 1, reason)
                self._burn_streak = 0
        else:
            self._burn_streak = 0
            if self._degrade_level == 0:
                return
            self._calm_streak += 1
            if (self._calm_streak
                    >= int(self.config.degrade_recover_patience)):
                self._set_degrade_level(self._degrade_level - 1,
                                        "recovered")
                self._calm_streak = 0

    def _set_degrade_level(self, level, reason):
        prev, level = self._degrade_level, int(level)
        self._degrade_level = level
        self._degrade_transitions += 1
        self._degrade_history.append(
            {"from": prev, "to": level,
             "level": DEGRADE_LEVELS[level], "reason": reason,
             "decode_horizons": self._decode_horizons})
        del self._degrade_history[:-64]
        name = self._profiler_name
        _SRV_DEGRADATION.set(level, engine=name)
        _obs_events.instant("serving.degrade", cat="serving",
                            engine=name, level=level,
                            level_name=DEGRADE_LEVELS[level],
                            from_level=prev, reason=reason)

    def _publish_gauges(self):
        """Refresh the point-in-time typed gauges (once per step — the
        counters/histograms above accumulate incrementally)."""
        name = self._profiler_name
        _SRV_DEGRADATION.set(self._degrade_level, engine=name)
        _SRV_QUEUE.set(self.scheduler.queue_depth, engine=name)
        _SRV_ACTIVE.set(self.cache.used_slots, engine=name)
        _SRV_KV_BLOCKS.set(self.pool.blocks_in_use, engine=name)
        _SRV_KV_OCC.set(self.pool.blocks_in_use / self.pool.capacity,
                        engine=name)
        if self.host_tier is not None:
            _SRV_HOST_OCC.set(self.host_tier.occupancy, engine=name)
        _SRV_BUCKETS.set(len(self._decode_buckets), engine=name)
        if self.config.spec_k:
            for slot in range(self.cache.num_slots):
                _SRV_SPEC_EMA.set(float(self._spec_ema[slot]),
                                  engine=name, lane=slot)
        if self._decode_steps:
            _SRV_UTIL.set(self._slot_busy_integral / self._decode_steps,
                          engine=name)
        if self._busy_s > 0:
            _SRV_TPS.set(self._tokens_generated / self._busy_s,
                         engine=name)
        if self._prompt_tokens:
            _SRV_PREFIX_RATIO.set(
                self._prefix_hit_tokens / self._prompt_tokens,
                engine=name)

    def run(self):
        """Drain the queue: step until every submitted request finished.
        Returns all requests retired during the drain."""
        out = []
        while self.scheduler.has_work:
            before = self._finished
            out.extend(self.step())
            if self._finished == before and not self.scheduler.running \
                    and self.scheduler.queue_depth \
                    and not self._admit_deferred:
                raise RuntimeError("engine stalled with queued work")
        return out

    def drain(self):
        """Graceful shutdown of admission: refuse new submissions, abort
        every still-QUEUED request (``finish_reason="abort"``, cause
        ``"drain"`` — they never claimed a slot), run the in-flight
        lanes to completion, then release every pool block the engine
        still references (the radix prefix store's unpinned chains are
        reclaimed) and verify ``kv_blocks_in_use == 0`` — the block-leak
        invariant a replica must satisfy before the router removes it.

        Returns every request retired during the drain (aborted queued
        requests first, then lanes in retirement order).  The engine is
        empty but fully usable afterwards: ``submit()`` works again once
        ``drain()`` returns."""
        self._draining = True
        try:
            out = [self.abort(req, cause="drain")
                   for req in list(self.scheduler.queue)]
            # every running lane makes progress each step (preempted
            # lanes requeue as `resumed` and re-admit as slots free),
            # so this loop terminates within the remaining token budget
            while self.scheduler.has_work:
                out.extend(self.step())
        finally:
            self._draining = False
        # all leases are back, so every prefix chain is unpinned and
        # reclaimable; anything the reclaim cannot free is a leak.
        # The spill hook is disabled for this final sweep — shutdown
        # eviction is disposal, not demotion (demoting here would just
        # copy soon-to-be-cleared bytes into the host arena)
        spill, self.prefix.spill = self.prefix.spill, None
        spill_batch = self.prefix.spill_batch
        self.prefix.spill_batch = None
        try:
            self.prefix.reclaim(self.prefix._held)
        finally:
            self.prefix.spill = spill
            self.prefix.spill_batch = spill_batch
        if self.pool.blocks_in_use != 0:
            raise RuntimeError(
                f"drain() left {self.pool.blocks_in_use} KV pool blocks "
                f"referenced ({self.cache.leased_blocks} leased by slot "
                f"tables, {self.prefix._held} pinned by the prefix "
                "store) — block-leak invariant violated")
        if self.host_tier is not None:
            # the host-tier extension of the block-leak invariant:
            # demoted prefix entries are disposable cache content, but
            # any block still referenced after clearing them is a
            # leaked lane image (every request retired or aborted above
            # dropped its image)
            self.host_tier.clear_prefixes()
            if self.host_tier.blocks_in_use != 0:
                raise RuntimeError(
                    f"drain() left {self.host_tier.blocks_in_use} host "
                    f"arena blocks referenced "
                    f"({len(self.host_tier._lanes)} lane images) — "
                    "host block-leak invariant violated")
        self._publish_gauges()
        return out

    def generate(self, prompts, sampling=None):
        """Convenience wrapper: one prompt (list of ids) or a batch
        (list of lists).  Submits, drains, and returns the generated ids
        — a list per prompt, in submission order."""
        single = bool(prompts) and np.isscalar(prompts[0])
        batch = [prompts] if single else list(prompts)
        if isinstance(sampling, (list, tuple)):
            reqs = [self.submit(p, s) for p, s in zip(batch, sampling)]
        else:
            reqs = [self.submit(p, sampling) for p in batch]
        self.run()
        outs = [r.output_ids for r in reqs]
        return outs[0] if single else outs

    # ------------------------------------------------------------ bench
    def measure_decode_seconds(self, horizon, iters=3):
        """Benchmark hook: best wall seconds for ONE compiled horizon
        dispatch (including its single host sync) over the engine's
        current device state.  Advances the cache/state buffers, so call
        it only after draining — it exists to separate device time from
        the engine's host-side per-horizon overhead."""
        h = self._resolve_horizon(horizon)
        best = None
        for _ in range(iters):
            t0 = time.perf_counter()
            self._dispatch_horizon(h)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    # ------------------------------------------------------------ metrics
    # ------------------------------------------------- memory accounting
    @staticmethod
    def _tree_bytes(tree):
        """Device bytes over a pytree of arrays (None leaves drop out of
        jax.tree.leaves; a deleted buffer still reports its aval size)."""
        total = 0
        for leaf in jax.tree.leaves(tree):
            try:
                total += int(leaf.nbytes)
            except Exception:        # pragma: no cover - defensive
                continue
        return total

    def _kv_pool_bytes(self):
        p = self.pool
        return self._tree_bytes([p.k, p.v, p.k_scale, p.v_scale])

    def _weight_device_bytes(self):
        return self._tree_bytes(self._state_arrays)

    def _state_device_bytes(self):
        return self._tree_bytes([
            self._d_tokens, self._d_pos, self._d_counts, self._d_active,
            self._d_hist, self._d_gates, self._d_params, self._d_tables,
            self._d_dfa_state, self._d_dfa_next, self._d_dfa_mask,
            self._d_dfa_forced])

    def counters(self):
        """Observability snapshot (also exposed via
        paddle_tpu.profiler.counters())."""
        c = {
            "queue_depth": self.scheduler.queue_depth,
            "active_slots": self.cache.used_slots,
            "num_slots": self.cache.num_slots,
            "requests_finished": self._finished,
            "tokens_generated": self._tokens_generated,
            "decode_steps": self._decode_steps,
            "decode_horizons": self._decode_horizons,
            "decode_calls": self._decode.calls,
            "decode_host_syncs": self._host_syncs,
            "wasted_lane_tokens": self._wasted_lane_tokens,
            "prefill_calls": self._prefill_calls,
            "prefill_requests": self._prefill_requests,
            "prefill_chunk_dispatches": self._chunk_dispatches,
            "prefill_chunked_requests": self._chunked_requests,
            "prefix_hit_tokens": self._prefix_hit_tokens,
            "prompt_tokens": self._prompt_tokens,
            "prefix_hit_ratio": (
                self._prefix_hit_tokens / self._prompt_tokens
                if self._prompt_tokens else 0.0),
            "decode_compiles": self._decode.misses,
            "decode_cache_hits": self._decode.hits,
            "prefill_compiles": self._prefill.misses,
            "prefill_cache_hits": self._prefill.hits,
            # unified pool: caching new prefix blocks is adopt()
            # refcounting, so the old scatter-insert dispatch is gone
            "prefix_insert_calls": 0,
            "kv_blocks_in_use": self.pool.blocks_in_use,
            "kv_bytes_read": self._kv_bytes_read,
            "cow_copies": self._cow_copies,
            "preemptions": self._preemptions,
            "kv_swap_ins": self._swap_ins,
            "kv_swap_outs": self._swap_outs,
            "kv_swap_in_bytes": self._swap_in_bytes,
            "kv_swap_out_bytes": self._swap_out_bytes,
            "requests_aborted": self._aborted,
            "deadline_expired": self._deadline_expired,
            "spec_draft_tokens": self._spec_draft_tokens,
            "spec_accepted_tokens": self._spec_accepted_tokens,
            "spec_accept_rate": (
                self._spec_accepted_tokens / self._spec_draft_tokens
                if self._spec_draft_tokens else 0.0),
            "spec_forced_tokens": self._spec_forced_tokens,
            "degradation_level": self._degrade_level,
            "degradation_sheds": self._degrade_sheds,
        }
        if self._decode_steps:
            c["slot_utilization"] = (self._slot_busy_integral
                                     / self._decode_steps)
        if self._ttft_n:
            c["ttft_avg_s"] = self._ttft_sum / self._ttft_n
        if self._busy_s > 0:
            c["tokens_per_s"] = self._tokens_generated / self._busy_s
        return c

    def tenant_ledger(self):
        """The per-tenant accounting ledger (tenant None bills to "")
        as a cheap copy — the gateway republishes it as
        ``gateway.tenant_tokens_served`` gauges and the fleet replay
        harness reconciles streamed tokens against it, without paying
        for a full ``stats()`` pass."""
        return {k: dict(v) for k, v in self._tenants.items()}

    def stats(self):
        """counters() plus derived stats: the distinct compiled horizon
        buckets, the fraction of scanned lane steps wasted on lanes that
        had already retired mid-horizon, prefix-cache internals, and
        exact TTFT percentiles from the observability reservoir."""
        s = dict(self.counters())
        lane_steps = self._decode_harvested + self._wasted_lane_tokens
        s["wasted_lane_fraction"] = (
            self._wasted_lane_tokens / lane_steps if lane_steps else 0.0)
        s["horizon_buckets"] = sorted(self._horizon_buckets)
        s["decode_buckets"] = sorted(self._decode_buckets)
        s["next_horizon_growth"] = self._grow
        s["prefill"] = {
            "chunk_tokens": self._chunk_tokens,
            "chunks_in_flight": len(self._chunking),
            "chunk_dispatches": self._chunk_dispatches,
            "chunked_requests": self._chunked_requests,
            "chunk_count_total": self._chunk_count_total,
            "interference_seconds": self._prefill_interference_s,
            "context_high_water": self._context_high_water,
            # every (lanes, bucket) prefill program this engine ran —
            # with chunking on, no bucket exceeds chunk_tokens, which is
            # what bounds a long prompt's hold on the engine
            "buckets": sorted(self._prefill_buckets),
        }
        s["prefix"] = self.prefix.stats()
        # gateway-era admission fields: per-tenant accounting (tenant
        # None bills to "") and the deadline-abort tally; priorities
        # live on the requests themselves and in their QUEUED trace
        # events
        s["tenants"] = self.tenant_ledger()
        s["draining"] = self._draining
        s["degradation"] = {
            "level": self._degrade_level,
            "level_name": DEGRADE_LEVELS[self._degrade_level],
            "transitions": self._degrade_transitions,
            "sheds": self._degrade_sheds,
            "history": list(self._degrade_history[-8:]),
        }
        s["kv_pool"] = {
            "block_size": self._block_size,
            "capacity_blocks": self.pool.capacity,
            "free_blocks": self.pool.free_blocks,
            "blocks_in_use": self.pool.blocks_in_use,
            "leased_blocks": self.cache.leased_blocks,
            "cached_blocks": self.prefix._held,
            "bytes_per_block": self.pool.bytes_per_block,
            "kv_bytes_read": self._kv_bytes_read,
            "cow_copies": self._cow_copies,
            "preemptions": self._preemptions,
            "dtype": str(jnp.dtype(self.pool.store_dtype)),
            "quant_dtype": self.pool.quant_dtype,
        }
        # tiered KV: the host spill arena under the pool.  Counters are
        # trace-exact per kind: kv_swap_out_bytes covers lane saves
        # (paired SWAP_OUT trace events), demote_bytes covers prefix
        # demotions (engine-level, no owning request).
        tier = self.host_tier
        s["kv_pool"].update({
            "host_capacity_blocks": tier.capacity if tier else 0,
            "host_blocks_in_use": tier.blocks_in_use if tier else 0,
            "host_arena_bytes": self._host_arena_bytes(),
            "host_occupancy_ratio": tier.occupancy if tier else 0.0,
            "kv_swap_ins": self._swap_ins,
            "kv_swap_outs": self._swap_outs,
            "kv_swap_in_blocks": self._swap_in_blocks,
            "kv_swap_out_blocks": self._swap_out_blocks,
            "kv_swap_in_bytes": self._swap_in_bytes,
            "kv_swap_out_bytes": self._swap_out_bytes,
            "kv_demote_bytes": self._demote_bytes,
            "kv_swaps_averted_tokens": self._swaps_averted_tokens,
            "kv_swaps_averted_flops": self._swaps_averted_flops,
            "swap_policy": self._swap_policy,
        })
        if tier is not None:
            s["kv_pool"]["host_tier"] = tier.stats()
        s["quant"] = {
            "weight_dtype": self._weight_dtype,
            "kv_cache_dtype": self._kv_quant,
            "quantized_weights": len(self._wq_dtypes),
            # actual bytes the decode step streams for parameters —
            # int8 payload + scale vectors for quantized entries, fp
            # bytes for the rest
            "weight_bytes": int(sum(
                sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(a))
                for a in self._state_arrays)),
        }
        s["spec"] = {
            "k": int(self.config.spec_k),
            "adaptive": bool(self.config.spec_adaptive),
            "ngram": int(self.config.spec_ngram),
            "draft_tokens": self._spec_draft_tokens,
            "accepted_tokens": self._spec_accepted_tokens,
            "accept_rate": (
                self._spec_accepted_tokens / self._spec_draft_tokens
                if self._spec_draft_tokens else 0.0),
            # tokens emitted per verify window (accepted prefix + the
            # bonus token) -> number of windows, drafting lanes only
            "accept_len_hist": {
                int(n): c
                for n, c in sorted(self._spec_accept_hist.items())},
            "mean_accept_len": (
                sum(n * c for n, c in self._spec_accept_hist.items())
                / self._spec_windows if self._spec_windows else 0.0),
            "lane_accept_ema": [round(float(x), 4)
                                for x in self._spec_ema],
        }
        slab = self._grammar_slab
        s["structured"] = {
            "enabled": self._structured,
            # lanes currently decoding under a grammar: active with a
            # non-sentinel DFA state
            "constrained_lanes": int(sum(
                1 for slot in range(self.cache.num_slots)
                if self._active[slot] and self._dfa_state[slot] != 0)),
            "capacity_states": slab.capacity if slab else 0,
            "states_used": slab.states_used if slab else 0,
            "grammars_installed": slab.grammars_installed if slab else 0,
            "table_bytes": slab.device_bytes if slab else 0,
            "compile_cache_hits": self._grammar_cache_hits,
            "compile_cache_misses": self._grammar_cache_misses,
            "compile_cache_entries": len(self._grammar_cache),
            "forced_tokens": self._spec_forced_tokens,
        }
        # observability phase 3: program-card cost model + memory ledger
        s["cost"] = {
            "program_flops_total": self._program_flops,
            "program_bytes_total": self._program_bytes,
            "decode_cards": len({id(c) for c in
                                 self._decode.cards.values()}),
            "prefill_cards": len({id(c) for c in
                                  self._prefill.cards.values()}),
        }
        s["memory"] = self.ledger.snapshot()
        qp50 = _SRV_QUEUE_WAIT.percentile(50, engine=self._profiler_name)
        if qp50 is not None:
            s["queue_wait_p50_s"] = qp50
            s["queue_wait_p95_s"] = _SRV_QUEUE_WAIT.percentile(
                95, engine=self._profiler_name)
        if self._ttft_n:
            s["ttft_p50_s"] = _SRV_TTFT.percentile(
                50, engine=self._profiler_name)
            s["ttft_p95_s"] = _SRV_TTFT.percentile(
                95, engine=self._profiler_name)
        if self.slo is not None:
            s["slo"] = self.slo.snapshot()
        if self.recorder is not None:
            s["tracing"] = {
                "live_traces": len(self.recorder.live()),
                "finished_retained": len(self.recorder.recent()),
                "dropped_finished": self.recorder.dropped,
                "capacity": self.recorder.capacity,
            }
        if self.telemetry is not None:
            s["telemetry_port"] = self.telemetry.port
        return s
