"""The continuous-batching LLM inference engine.

Architecture (prefill/decode split over a slotted static-shape cache):

* **Prefill** — each admitted request runs one ``[1, bucket]`` forward
  that writes its prompt's k/v into its slot row and samples the first
  token.  Prompts are right-padded to power-of-two length buckets, so
  there is exactly ONE compiled prefill program per bucket, reused by
  every request whose prompt falls in it (heterogeneous prompt lengths
  stop being a retrace source).
* **Decode** — ONE fused step over ALL slot rows: embed the last token
  of every slot, run the model with per-row positions against the full
  ``[num_slots, max_seq_len, kv_heads, head_dim]`` buffers (written via
  ``dynamic_update_slice``), and sample per-request tokens under
  per-request seeded PRNG.  Every step of every request mix has the same
  input signature, so the step compiles exactly once.
* **Continuous batching** — requests join at decode-step boundaries and
  free their slot on EOS/max-tokens; the admission queue drains into
  freed slots between steps (scheduler.py).

The engine reuses the model's own Layer code (functionalized through
``use_state``, the TrainStep pattern), so slotted decode is numerically
the decode path models/gpt.py already ships — just with a cache the
compiler can keep static.
"""

from __future__ import annotations

import time
import weakref
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from ..core import tape as _tape
from ..core.tensor import Tensor
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics
from ..observability.span import span as _obs_span
from .kv_cache import SlotKV, SlottedKVCache
from .sampling import SamplingParams, request_key, sample_batch, sample_token
from .scheduler import Scheduler

# typed registry families the engine publishes into (labeled by engine
# instance so two engines in one process stay distinguishable); the
# legacy flat counters() dict stays as the profiler-facade back-compat
# surface
_SRV_TOKENS = _obs_metrics.counter(
    "serving.tokens_generated", "tokens sampled across prefill+decode")
_SRV_REQS = _obs_metrics.counter(
    "serving.requests_finished", "requests retired (EOS or max-tokens)")
_SRV_DECODE_STEPS = _obs_metrics.counter(
    "serving.decode_steps", "fused decode steps executed")
_SRV_PREFILL = _obs_metrics.counter(
    "serving.prefill_calls", "per-request prefill passes")
_SRV_QUEUE = _obs_metrics.gauge(
    "serving.queue_depth", "requests waiting for a slot")
_SRV_ACTIVE = _obs_metrics.gauge(
    "serving.active_slots", "slots currently decoding")
_SRV_UTIL = _obs_metrics.gauge(
    "serving.slot_utilization", "mean active/total slots over decode steps")
_SRV_TPS = _obs_metrics.gauge(
    "serving.tokens_per_s", "generated tokens per engine-busy second")
_SRV_TTFT = _obs_metrics.histogram(
    "serving.ttft_seconds", "submit-to-first-token wall seconds")
_SRV_STEP = _obs_metrics.histogram(
    "serving.step_seconds", "wall seconds per engine step()")
# compile/cache families SHARED with jit/api.py: one place answers
# "which function retraced" for both to_static and serving programs
_COMPILE_COUNT = _obs_metrics.counter(
    "jit.compile_count", "to_static trace+compile builds, by function")
_CACHE_HIT = _obs_metrics.counter(
    "jit.cache_hit", "to_static calls served from the jit cache")
_COMPILE_SECONDS = _obs_metrics.histogram(
    "jit.compile_seconds",
    "wall seconds from cache miss to first result, by function")


class CompiledFn:
    """jax.jit wrapper that counts compile-cache hits/misses by input
    signature (shape+dtype of every array leaf).  The miss counter is the
    engine's observable proof of static-shape serving: a multi-request
    run with heterogeneous prompt lengths must show decode misses == 1
    and prefill misses == number of distinct buckets.  Hits/misses also
    land on the typed registry (``jit.compile_count`` / ``jit.cache_hit``
    labeled ``fn=name``) and every miss leaves a retrace-cause event plus
    a compile begin/end pair on the timeline."""

    def __init__(self, fn, donate_argnums=(), name=None):
        self._jit = jax.jit(fn, donate_argnums=donate_argnums)
        self._name = name or getattr(fn, "__name__", "fn")
        self._seen = set()
        self.misses = 0
        self.hits = 0

    @staticmethod
    def _signature(args):
        return tuple((tuple(jnp.shape(a)), str(jnp.result_type(a)))
                     for a in jax.tree.leaves(args))

    def __call__(self, *args):
        sig = self._signature(args)
        if sig in self._seen:
            self.hits += 1
            _CACHE_HIT.inc(fn=self._name)
            return self._jit(*args)
        self._seen.add(sig)
        self.misses += 1
        _obs_events.instant(
            "jit.retrace", cat="serving", fn=self._name,
            cause=("first_call" if self.misses == 1
                   else "new_input_signature"),
            cached_signatures=len(self._seen) - 1)
        _obs_events.begin("jit.compile", cat="serving", fn=self._name)
        t0 = time.perf_counter()
        try:
            return self._jit(*args)
        finally:
            dt = time.perf_counter() - t0
            _COMPILE_COUNT.inc(fn=self._name)
            _COMPILE_SECONDS.observe(dt, fn=self._name)
            _obs_events.end("jit.compile", cat="serving", fn=self._name,
                            seconds=round(dt, 9))


@dataclass
class EngineConfig:
    num_slots: int = 8
    max_seq_len: int = 256
    #: smallest prefill bucket; prompts pad up to the next power of two
    min_prefill_bucket: int = 8
    #: kv cache dtype; None = the model's parameter dtype
    cache_dtype: object = None


class Engine:
    """Submit/step/generate over a causal-LM Layer (GPTForCausalLM /
    LlamaForCausalLM or anything with ``.model``, ``.config`` and
    ``._logits``)."""

    _instances = 0

    def __init__(self, model, config=None, register_profiler=True):
        self.model = model
        self.config = config or EngineConfig()
        model.eval()
        mc = model.config
        self._state_names = list(model.state_dict().keys())
        sd = model.state_dict()
        self._state_arrays = [sd[n]._data for n in self._state_names]
        cache_dtype = (self.config.cache_dtype
                       or model.model.embed_tokens.weight._data.dtype)
        self.cache = SlottedKVCache(
            num_layers=len(model.model.layers),
            num_slots=self.config.num_slots,
            max_seq_len=self.config.max_seq_len,
            kv_heads=mc.kv_heads, head_dim=mc.head_dim,
            dtype=cache_dtype)
        self.scheduler = Scheduler(self.config.num_slots)

        n = self.config.num_slots
        self._tokens = np.zeros(n, np.int32)        # last token per slot
        self._pos = np.zeros(n, np.int32)           # row length per slot
        self._seeds = np.zeros(n, np.uint32)
        self._counts = np.zeros(n, np.int32)        # tokens sampled so far
        self._temps = np.zeros(n, np.float32)
        self._top_ks = np.zeros(n, np.int32)
        self._top_ps = np.ones(n, np.float32)

        # donation buys in-place HBM cache updates on accelerators; CPU
        # would only warn that donation is unimplemented
        donate = jax.default_backend() not in ("cpu",)
        self._decode = CompiledFn(self._decode_fn,
                                  donate_argnums=(3, 4) if donate else (),
                                  name="serving.decode")
        self._prefill = CompiledFn(self._prefill_fn,
                                   donate_argnums=(4, 5) if donate else (),
                                   name="serving.prefill")

        # observability
        self._decode_steps = 0
        self._prefill_calls = 0
        self._tokens_generated = 0
        self._busy_s = 0.0
        self._slot_busy_integral = 0.0   # sum over steps of used/num
        self._finished = 0
        self._ttft_sum = 0.0
        self._ttft_n = 0

        Engine._instances += 1
        self._profiler_name = f"serving.engine{Engine._instances}"
        self._finalizer = None
        if register_profiler:
            from .. import profiler as _profiler

            # the provider must NOT keep the engine alive (a bound method
            # in a process-global registry pins the engine — and its full
            # KV cache — forever): register a weakref-backed provider and
            # let GC unregister it, so repeated engine construction in
            # one process never leaks stale providers into
            # profiler.counters()
            ref = weakref.ref(self)

            def _provider():
                eng = ref()
                return eng.counters() if eng is not None else {}

            _profiler.register_counter_provider(self._profiler_name,
                                                _provider)
            self._finalizer = weakref.finalize(
                self, _profiler.unregister_counter_provider,
                self._profiler_name)

    def close(self):
        """Unregister this engine's counter provider (idempotent; also
        runs automatically when the engine is garbage-collected)."""
        if self._finalizer is not None:
            self._finalizer()

    # ------------------------------------------------------------ pure fns
    def _run_model(self, state_arrays, ids, views):
        """Functionalized forward: raw param arrays + token ids + SlotKV
        views -> (last-position logits [B, vocab], new views)."""
        arrays = dict(zip(self._state_names, state_arrays))
        with _tape.no_grad():
            with self.model.use_state(arrays):
                h, new_views = self.model.model(Tensor(ids), caches=views)
                logits = self.model._logits(h)
        return logits._data, new_views

    def _prefill_fn(self, state_arrays, ids, length, slot, cache_k,
                    cache_v, seed, temp, top_k, top_p):
        """One request's prompt pass: ids [1, bucket] (right-padded),
        fresh zero slot row, write k/v for every prompt position, sample
        the first token from the last VALID position's logits, scatter
        the row into the full cache at ``slot``."""
        row_shape = (1, self.cache.max_seq_len, self.cache.kv_heads,
                     self.cache.head_dim)
        pos0 = jnp.zeros((1,), jnp.int32)
        views = [SlotKV(jnp.zeros(row_shape, self.cache.dtype),
                        jnp.zeros(row_shape, self.cache.dtype), pos0)
                 for _ in range(self.cache.num_layers)]
        logits, new_views = self._run_model(state_arrays, ids, views)
        last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                            axis=0, keepdims=False)
        first = sample_token(last, request_key(seed, 0), temp, top_k,
                             top_p)
        new_k = [jax.lax.dynamic_update_slice(
                     ck, nv.k, (slot, 0, 0, 0))
                 for ck, nv in zip(cache_k, new_views)]
        new_v = [jax.lax.dynamic_update_slice(
                     cv, nv.v, (slot, 0, 0, 0))
                 for cv, nv in zip(cache_v, new_views)]
        return first, new_k, new_v

    def _decode_fn(self, state_arrays, tokens, pos, cache_k, cache_v,
                   seeds, counts, temps, top_ks, top_ps):
        """The ONE fused decode step over all slots: static shapes
        everywhere, per-row positions, per-request sampling."""
        views = [SlotKV(ck, cv, pos)
                 for ck, cv in zip(cache_k, cache_v)]
        logits, new_views = self._run_model(state_arrays, tokens[:, None],
                                            views)
        nxt = sample_batch(logits[:, 0], seeds, counts, temps, top_ks,
                           top_ps)
        return nxt, [v.k for v in new_views], [v.v for v in new_views]

    # ------------------------------------------------------------ buckets
    def _bucket(self, prompt_len):
        b = self.config.min_prefill_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.config.max_seq_len)

    # ------------------------------------------------------------ API
    def submit(self, prompt_ids, sampling=None):
        """Queue one request; returns the Request handle (its
        ``output_ids`` fill in as the engine steps)."""
        prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt_ids:
            raise ValueError("empty prompt")
        sampling = sampling or SamplingParams()
        if len(prompt_ids) + sampling.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt_len {len(prompt_ids)} + max_new_tokens "
                f"{sampling.max_new_tokens} exceeds max_seq_len "
                f"{self.config.max_seq_len}")
        req = self.scheduler.submit(prompt_ids, sampling)
        _SRV_QUEUE.set(self.scheduler.queue_depth,
                       engine=self._profiler_name)
        return req

    def _admit(self):
        for req in self.scheduler.admissible(self.cache.free_slots):
            slot = self.cache.alloc()
            self.scheduler.start(req, slot)
            bucket = self._bucket(req.prompt_len)
            _obs_events.instant("serving.slot_alloc", cat="serving",
                                slot=slot, request=req.request_id,
                                prompt_len=req.prompt_len, bucket=bucket)
            # async span: a request's life overlaps other requests on
            # this thread, so it pairs by id, not by B/E nesting
            _obs_events.record(
                "serving.request", phase=_obs_events.ASYNC_BEGIN,
                cat="serving", id=req.request_id,
                args={"slot": slot, "prompt_len": req.prompt_len})
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :req.prompt_len] = req.prompt_ids
            with _obs_span("serving.prefill_pass", cat="serving",
                           event_args={"request": req.request_id,
                                       "bucket": bucket}):
                first, new_k, new_v = self._prefill(
                    self._state_arrays, jnp.asarray(ids),
                    jnp.asarray(req.prompt_len, jnp.int32),
                    jnp.asarray(slot, jnp.int32),
                    self.cache.k, self.cache.v,
                    jnp.asarray(req.sampling.seed, jnp.uint32),
                    jnp.asarray(req.sampling.temperature, jnp.float32),
                    jnp.asarray(req.sampling.top_k, jnp.int32),
                    jnp.asarray(req.sampling.top_p, jnp.float32))
            self.cache.rebind(new_k, new_v)
            self._prefill_calls += 1
            self._tokens_generated += 1
            _SRV_PREFILL.inc(engine=self._profiler_name)
            _SRV_TOKENS.inc(engine=self._profiler_name)
            tok = int(np.asarray(first))
            if req.record_token(tok):
                self._retire(req)
                continue
            s = req.sampling
            self._tokens[slot] = tok
            self._pos[slot] = req.prompt_len
            self._seeds[slot] = np.uint32(s.seed)
            self._counts[slot] = req.n_generated
            self._temps[slot] = s.temperature
            self._top_ks[slot] = s.top_k
            self._top_ps[slot] = s.top_p

    def _retire(self, req):
        self.cache.free(req.slot)
        self.scheduler.finish(req)
        self._finished += 1
        self._ttft_sum += req.ttft
        self._ttft_n += 1
        _SRV_REQS.inc(engine=self._profiler_name)
        _SRV_TTFT.observe(req.ttft, engine=self._profiler_name)
        _obs_events.instant("serving.slot_retire", cat="serving",
                            slot=req.slot, request=req.request_id,
                            reason=req.finish_reason,
                            n_generated=req.n_generated)
        _obs_events.record(
            "serving.request", phase=_obs_events.ASYNC_END,
            cat="serving", id=req.request_id,
            args={"reason": req.finish_reason,
                  "n_generated": req.n_generated,
                  "ttft_s": round(req.ttft, 6)})
        # park the freed slot on a masked no-op row until reassigned
        slot = req.slot
        self._tokens[slot] = 0
        self._pos[slot] = 0
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._top_ps[slot] = 1.0
        self._counts[slot] = 0
        self._seeds[slot] = 0

    def step(self):
        """One engine iteration: admit queued requests into free slots
        (prefill), then run one fused decode step over every active slot.
        Returns the requests that finished during this step."""
        t0 = time.time()
        finished = []
        self._admit()
        active = dict(self.scheduler.running)
        if active:
            nxt, new_k, new_v = self._decode(
                self._state_arrays,
                jnp.asarray(self._tokens), jnp.asarray(self._pos),
                self.cache.k, self.cache.v,
                jnp.asarray(self._seeds), jnp.asarray(self._counts),
                jnp.asarray(self._temps), jnp.asarray(self._top_ks),
                jnp.asarray(self._top_ps))
            self.cache.rebind(new_k, new_v)
            nxt = np.asarray(nxt)
            self._decode_steps += 1
            self._slot_busy_integral += len(active) / self.cache.num_slots
            _SRV_DECODE_STEPS.inc(engine=self._profiler_name)
            _SRV_TOKENS.inc(len(active), engine=self._profiler_name)
            for slot, req in active.items():
                self._tokens_generated += 1
                # the decode step wrote this token's k/v at pos[slot]
                self._pos[slot] += 1
                if req.record_token(nxt[slot]):
                    self._retire(req)
                    finished.append(req)
                else:
                    self._tokens[slot] = nxt[slot]
                    self._counts[slot] = req.n_generated
        dt = time.time() - t0
        self._busy_s += dt
        _SRV_STEP.observe(dt, engine=self._profiler_name)
        self._publish_gauges()
        return finished

    def _publish_gauges(self):
        """Refresh the point-in-time typed gauges (once per step — the
        counters/histograms above accumulate incrementally)."""
        name = self._profiler_name
        _SRV_QUEUE.set(self.scheduler.queue_depth, engine=name)
        _SRV_ACTIVE.set(self.cache.used_slots, engine=name)
        if self._decode_steps:
            _SRV_UTIL.set(self._slot_busy_integral / self._decode_steps,
                          engine=name)
        if self._busy_s > 0:
            _SRV_TPS.set(self._tokens_generated / self._busy_s,
                         engine=name)

    def run(self):
        """Drain the queue: step until every submitted request finished.
        Returns all requests retired during the drain."""
        out = []
        while self.scheduler.has_work:
            before = self._finished
            out.extend(self.step())
            if self._finished == before and not self.scheduler.running \
                    and self.scheduler.queue_depth:
                raise RuntimeError("engine stalled with queued work")
        return out

    def generate(self, prompts, sampling=None):
        """Convenience wrapper: one prompt (list of ids) or a batch
        (list of lists).  Submits, drains, and returns the generated ids
        — a list per prompt, in submission order."""
        single = bool(prompts) and np.isscalar(prompts[0])
        batch = [prompts] if single else list(prompts)
        if isinstance(sampling, (list, tuple)):
            reqs = [self.submit(p, s) for p, s in zip(batch, sampling)]
        else:
            reqs = [self.submit(p, sampling) for p in batch]
        self.run()
        outs = [r.output_ids for r in reqs]
        return outs[0] if single else outs

    # ------------------------------------------------------------ metrics
    def counters(self):
        """Observability snapshot (also exposed via
        paddle_tpu.profiler.counters())."""
        c = {
            "queue_depth": self.scheduler.queue_depth,
            "active_slots": self.cache.used_slots,
            "num_slots": self.cache.num_slots,
            "requests_finished": self._finished,
            "tokens_generated": self._tokens_generated,
            "decode_steps": self._decode_steps,
            "prefill_calls": self._prefill_calls,
            "decode_compiles": self._decode.misses,
            "decode_cache_hits": self._decode.hits,
            "prefill_compiles": self._prefill.misses,
            "prefill_cache_hits": self._prefill.hits,
        }
        if self._decode_steps:
            c["slot_utilization"] = (self._slot_busy_integral
                                     / self._decode_steps)
        if self._ttft_n:
            c["ttft_avg_s"] = self._ttft_sum / self._ttft_n
        if self._busy_s > 0:
            c["tokens_per_s"] = self._tokens_generated / self._busy_s
        return c
