"""paddle_tpu.serving — continuous-batching LLM inference engine.

The serving subsystem the reference ships as AnalysisPredictor + the
fused CUDA decode ops (fused_multi_transformer), rebuilt TPU-native
around three ideas the benches point at (DECODE_BENCH.json):

* a **unified paged KV pool** (kv_cache.py) — all KV in ONE per-layer
  ``[num_blocks, block_size, kv_heads, head_dim]`` pool (vLLM-style
  fixed blocks) addressed through per-slot block tables; table entries
  are allocated lazily, so HBM scales with live tokens, and every
  block is host-refcounted (table entries and the prefix store each
  hold a reference).  The slotted static-shape cache
  (:class:`SlottedKVCache`) remains as the simpler reference design;
* **ragged paged-attention decode** (paged_attention.py) — decode
  attention reads ONLY each lane's table-mapped blocks (Pallas kernel
  on TPU, an XLA online-softmax fallback on CPU whose exact-zero
  masking makes outputs bitwise-invariant to the static table width),
  so per-step KV bandwidth tracks live sequence length, not
  ``max_seq_len``;
* a **prefill/decode split** with power-of-two prefill buckets — one
  compiled prefill per (lane-bucket, length-bucket) pair (engine.py);
* **batched fused prefill** — admission groups same-bucket queued
  requests (``Scheduler.pop_batch``, bounded reorder window so FIFO
  order is never violated by more than ``reorder_window`` overtakes)
  and prefills the whole group in ONE compiled dispatch;
* a **copy-free prefix KV cache** (prefix_cache.py) — a block-granular
  radix store over prompt token ids (RadixAttention-style reuse over
  vLLM-style fixed blocks) holding refcounted blocks of the unified
  pool: a hit leases cached blocks straight into the slot's block
  table (zero copies; a partial tail match is copy-on-write), caching
  new content is ``adopt()`` refcounting, and unpinned blocks are
  LRU-evicted under ``prefix_cache_bytes``;
* **continuous batching + preemption** — FIFO admission into a fixed
  slot pool, requests join at horizon boundaries and release their
  blocks on EOS or max-tokens (scheduler.py), with greedy/temperature/
  top-k/top-p sampling under per-request seeded PRNG (sampling.py);
  under block pressure the engine preempts the youngest lane
  (``Engine.preempt``: blocks released, request requeued at the front,
  re-admission reproduces its stream bitwise);
* **horizon-scanned fused decode** — ``Engine.step(horizon=H)`` runs H
  decode steps as one compiled ``lax.scan`` over device-resident engine
  state with the pool as donated carry: one dispatch and one host sync
  per horizon instead of per token, with per-slot EOS/max-token masking
  inside the scan.  An adaptive policy shrinks the horizon to 1 while
  requests are queued and grows it toward ``EngineConfig.max_horizon``
  when the slot mix is stable.  ``fold_in(seed, n_generated)`` PRNG
  keeps every horizon bitwise-equal to per-step decode;
* **self-drafting speculative decode** (drafter.py + engine.py) — with
  ``EngineConfig.spec_k = K > 0`` each fused step verifies a
  ``K+1``-token window per lane: a traced prompt-lookup drafter
  proposes K tokens from the lane's own history, one forward scores
  all K+1 positions through the same ragged paged-attention path, and
  the lane emits the longest matching draft prefix plus the model's
  own next token — 1..K+1 tokens per forward, greedy and seeded
  output bitwise-equal to ``spec_k=0``.  ``spec_adaptive`` gates
  low-acceptance lanes off and shrinks the dispatch back to plain
  decode when nobody's drafts are landing;
* **tensor-parallel sharded serving** (sharded/) — ``MeshEngine`` runs
  the whole engine over a ``("dp", "tp")`` device mesh: every Linear
  column-parallel (output-sharded), the paged KV pool sharded over
  kv_heads so each chip's block pool holds its head slice, per-layer
  attention combined through ONE disjoint-support psum, everything
  else through tiled all_gathers — greedy AND seeded output
  bitwise-equal to the single-chip engine under continuous batching,
  prefix hits, preemption and speculative decoding
  (:class:`~.sharded.ServingSpecLayout` holds the placement rules);
* an **HTTP/SSE front door** (gateway/) — an OpenAI-style
  ``/v1/completions`` endpoint with per-horizon SSE streaming, priority
  + deadline + per-tenant-quota admission (429/503 + Retry-After load
  shedding), and a prefix-affinity router over N in-process engine
  replicas (rendezvous-hashed radix-cache-block keys; SLO-unhealthy
  replicas stop receiving sessions).  Import from
  ``paddle_tpu.serving.gateway``;
* **structured generation** (structured/ + engine.py + drafter.py) —
  grammar-constrained decoding: a regex or JSON-schema request grammar
  compiles to a token-level DFA over the vocab (regex → NFA →
  minimized char DFA → vocab crossproduct, dense transitions + packed
  legality bitmask), per-lane DFA states ride the donated decode-scan
  carry like ``pos``/``counts``, and disallowed logits drop to a
  finite floor inside ``sample_window`` BEFORE the greedy fast path /
  categorical — constrained output is always grammar-valid, bitwise
  batched-vs-sequential under the same ``fold_in`` PRNG, and free
  lanes ride an accept-all sentinel state at zero cost.  States whose
  sole legal token is forced (JSON skeleton punctuation) feed the
  drafter ahead of its n-gram guesses (``forced_chain``), turning
  grammar structure into ~free speculative accepts.  With
  ``grammar_max_states=0`` every grammar argument threads ``None`` and
  the compiled programs are the unconstrained ones;
* **fault tolerance** (faults.py + gateway/router.py) — deterministic
  seeded fault injection (:class:`FaultPlan`/:class:`FaultInjector`:
  schedules keyed by dispatch ordinals, never wall clocks), a
  per-worker heartbeat watchdog, capped-exponential retry/backoff with
  deterministic jitter (:class:`RetryPolicy`), a graceful-degradation
  ladder (spec off → horizon 1 → shed) with hysteresis, and mid-stream
  replica **failover**: a dead replica's in-flight requests re-dispatch
  to survivors carrying prompt + tokens-already-streamed, resumed via
  re-prefill under the same ``fold_in(seed, n_generated)`` discipline —
  the continued stream is bitwise-identical to an uninterrupted run.

Quick start::

    from paddle_tpu.models import GPTForCausalLM, GPTConfig
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    engine = Engine(GPTForCausalLM(cfg),
                    EngineConfig(num_slots=8, max_seq_len=512,
                                 max_horizon=8))
    req = engine.submit(prompt_ids, SamplingParams(max_new_tokens=64))
    while engine.scheduler.has_work:
        engine.step()          # other submits land at horizon boundaries
    print(req.output_ids)

Counters (queue depth, TTFT, tokens/s, slot utilization, compile-cache
hits) are exposed through ``paddle_tpu.profiler.counters()``.
"""

from .drafter import draft_tokens, forced_chain
from .engine import CompiledFn, Engine, EngineConfig
from .faults import (FaultInjector, FaultPlan, FaultSpec, RetryPolicy,
                     TransientSubmitError, WorkerCrash, WorkerDeadError)
from .gateway import (EngineWorker, FleetSupervisor, Gateway,
                      GatewayConfig, PrefixAffinityRouter, TenantQuotas)
from .kv_cache import (PagedKV, PagedKVCache, PagedKVPool, SlotKV,
                       SlottedKVCache)
from .kv_host_tier import HostKVTier
from .paged_attention import paged_attention
from .prefix_cache import PrefixCache, PrefixLease
from .sampling import SamplingParams
from .scheduler import Request, Scheduler
from .sharded import MeshEngine, ServingSpecLayout
from .structured import (GrammarError, GrammarSlab, GrammarSpec,
                         TokenDFA, compile_grammar, compile_regex,
                         schema_to_regex)

__all__ = [
    "Engine", "EngineConfig", "CompiledFn",
    "PagedKV", "PagedKVCache", "PagedKVPool", "paged_attention",
    "HostKVTier",
    "SlotKV", "SlottedKVCache",
    "PrefixCache", "PrefixLease",
    "SamplingParams", "Request", "Scheduler",
    "draft_tokens", "forced_chain",
    "GrammarError", "GrammarSlab", "GrammarSpec", "TokenDFA",
    "compile_grammar", "compile_regex", "schema_to_regex",
    "Gateway", "GatewayConfig", "EngineWorker", "PrefixAffinityRouter",
    "TenantQuotas", "FleetSupervisor",
    "FaultPlan", "FaultSpec", "FaultInjector", "RetryPolicy",
    "WorkerCrash", "TransientSubmitError", "WorkerDeadError",
    "MeshEngine", "ServingSpecLayout",
]
