"""paddle_tpu.serving — continuous-batching LLM inference engine.

The serving subsystem the reference ships as AnalysisPredictor + the
fused CUDA decode ops (fused_multi_transformer), rebuilt TPU-native
around three ideas the benches point at (DECODE_BENCH.json):

* a **slotted static-shape KV cache** (kv_cache.py) — one compiled
  decode step for every step of every request mix, zero retracing;
* a **prefill/decode split** with power-of-two prefill buckets — one
  compiled prefill per (lane-bucket, length-bucket) pair (engine.py);
* **batched fused prefill** — admission groups same-bucket queued
  requests (``Scheduler.pop_batch``, bounded reorder window so FIFO
  order is never violated by more than ``reorder_window`` overtakes)
  and prefills the whole group in ONE compiled dispatch;
* a **prefix KV cache** (prefix_cache.py) — a block-granular radix
  store over prompt token ids (RadixAttention-style reuse over
  vLLM-style fixed-size blocks) backed by a device-resident block
  pool: a prompt extending a cached prefix gathers the cached KV into
  its slot row inside the prefill program and prefills only the
  suffix, bitwise-equal to full recomputation; blocks are refcounted
  while borrowed and LRU-evicted under ``prefix_cache_bytes``;
* **continuous batching** — FIFO admission into a fixed slot pool,
  requests join at horizon boundaries and free slots on EOS or
  max-tokens (scheduler.py), with greedy/temperature/top-k/top-p
  sampling under per-request seeded PRNG (sampling.py);
* **horizon-scanned fused decode** — ``Engine.step(horizon=H)`` runs H
  decode steps as one compiled ``lax.scan`` over device-resident engine
  state: one dispatch and one host sync per horizon instead of per
  token, with per-slot EOS/max-token masking inside the scan.  An
  adaptive policy shrinks the horizon to 1 while requests are queued
  and grows it toward ``EngineConfig.max_horizon`` when the slot mix is
  stable.  ``fold_in(seed, n_generated)`` PRNG keeps every horizon
  bitwise-equal to per-step decode.

Quick start::

    from paddle_tpu.models import GPTForCausalLM, GPTConfig
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    engine = Engine(GPTForCausalLM(cfg),
                    EngineConfig(num_slots=8, max_seq_len=512,
                                 max_horizon=8))
    req = engine.submit(prompt_ids, SamplingParams(max_new_tokens=64))
    while engine.scheduler.has_work:
        engine.step()          # other submits land at horizon boundaries
    print(req.output_ids)

Counters (queue depth, TTFT, tokens/s, slot utilization, compile-cache
hits) are exposed through ``paddle_tpu.profiler.counters()``.
"""

from .engine import CompiledFn, Engine, EngineConfig
from .kv_cache import SlotKV, SlottedKVCache
from .prefix_cache import PrefixCache, PrefixLease
from .sampling import SamplingParams
from .scheduler import Request, Scheduler

__all__ = [
    "Engine", "EngineConfig", "CompiledFn",
    "SlotKV", "SlottedKVCache",
    "PrefixCache", "PrefixLease",
    "SamplingParams", "Request", "Scheduler",
]
