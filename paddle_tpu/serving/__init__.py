"""paddle_tpu.serving — continuous-batching LLM inference engine.

The serving subsystem the reference ships as AnalysisPredictor + the
fused CUDA decode ops (fused_multi_transformer), rebuilt TPU-native
around three ideas the benches point at (DECODE_BENCH.json):

* a **slotted static-shape KV cache** (kv_cache.py) — one compiled
  decode step for every step of every request mix, zero retracing;
* a **prefill/decode split** with power-of-two prefill buckets — one
  compiled prefill per bucket (engine.py);
* **continuous batching** — FIFO admission into a fixed slot pool,
  requests join at decode-step boundaries and free slots on EOS or
  max-tokens (scheduler.py), with greedy/temperature/top-k/top-p
  sampling under per-request seeded PRNG (sampling.py).

Quick start::

    from paddle_tpu.models import GPTForCausalLM, GPTConfig
    from paddle_tpu.serving import Engine, EngineConfig, SamplingParams

    engine = Engine(GPTForCausalLM(cfg),
                    EngineConfig(num_slots=8, max_seq_len=512))
    req = engine.submit(prompt_ids, SamplingParams(max_new_tokens=64))
    while engine.scheduler.has_work:
        engine.step()          # other submits may land between steps
    print(req.output_ids)

Counters (queue depth, TTFT, tokens/s, slot utilization, compile-cache
hits) are exposed through ``paddle_tpu.profiler.counters()``.
"""

from .engine import CompiledFn, Engine, EngineConfig
from .kv_cache import SlotKV, SlottedKVCache
from .sampling import SamplingParams
from .scheduler import Request, Scheduler

__all__ = [
    "Engine", "EngineConfig", "CompiledFn",
    "SlotKV", "SlottedKVCache",
    "SamplingParams", "Request", "Scheduler",
]
