"""Shared-prefix KV reuse: a block-granular radix store over prompt
token ids, mapping cached prefixes to device-resident KV blocks.

The serving regime the ROADMAP targets — heavy traffic from millions of
users — is dominated by prompts that share long prefixes (system
prompts, few-shot preambles, chat history).  Recomputing the KV for a
shared prefix on every admission wastes exactly the work this module
caches: SGLang's RadixAttention (Zheng et al., 2023) keeps reusable KV
in a radix tree over token ids, and vLLM (Kwon et al., 2023) stores KV
in fixed-size blocks so reuse needs no reshapes.  This module combines
both ideas TPU-native:

* **Block pool** — per layer, ONE preallocated
  ``[capacity + 1, block_size, kv_heads, head_dim]`` k/v buffer pair.
  A cached prefix is a chain of block ids into that pool, so "copy the
  cached prefix into a request's slot row" is a single gather the
  engine traces INTO its batched prefill program (no extra dispatch).
  Block 0 is a reserved scratch block: padding lanes gather/scatter it
  freely, and nothing semantic ever reads it.
* **Radix store** — a trie whose edges are full blocks of
  ``block_size`` token ids (the hash-on-block-tokens formulation of a
  radix tree: shared prefixes share nodes, block-granular splits).
  Matching walks full blocks only and is capped at ``len(prompt) - 1``
  tokens, so an exact-hit prompt still prefills at least its final
  token (the logits source for its first sampled token).
* **LRU eviction under a byte budget** — capacity is
  ``budget_bytes // bytes_per_block``; when the free list runs dry the
  least-recently-used *unpinned leaf* is evicted (leaves only, so every
  cached chain stays reachable from the root).
* **Refcounts** — ``acquire()`` pins the matched chain while a slot
  borrows it; pinned nodes are never evicted.  ``insert()`` extends the
  lease over newly cached blocks; ``release()`` unpins on retirement.

Everything here is host-side bookkeeping over small python dicts; the
only device state is the block pool, which the engine's compiled
programs gather from (prefill) and scatter into (post-prefill insert).

**Unified-pool mode** (``pool=...``): instead of owning its own block
buffers, the radix store holds refcounted blocks of the engine's
:class:`~paddle_tpu.serving.kv_cache.PagedKVPool` — the SAME pool the
slot block tables point into.  Prefix hits become copy-free: the engine
leases matched blocks straight into a slot's block table
(``pool.share`` per borrow), and a partial tail match is served
copy-on-write (``lease.tail_block``/``tail_tokens``: the engine copies
that one block into the slot's private tail block inside the prefill
dispatch, then overwrites from offset ``tail_tokens`` on).  Caching new
content is ``adopt()`` — the radix store takes shared references on the
slot's freshly written private blocks — so the gather/scatter insert
path disappears entirely.  ``budget_bytes`` still bounds how many pool
blocks the store may hold; ``reclaim()`` lets the engine evict unpinned
leaves back to the free list under block pressure.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..observability import metrics as _obs_metrics

_SRV_PREFIX_EVICT = _obs_metrics.counter(
    "serving.prefix_evictions",
    "radix-store evictions by destination: dest=\"host\" demoted into "
    "the host spill arena, dest=\"dropped\" lost and recomputable only")


class _Node:
    """One full-block edge of the radix store."""

    __slots__ = ("tokens", "block", "parent", "children", "refcount",
                 "last_used")

    def __init__(self, tokens, block, parent):
        self.tokens = tokens          # tuple of block_size token ids
        self.block = block            # pool block id (>= 1; 0 is scratch)
        self.parent = parent
        self.children = {}            # block-token tuple -> _Node
        self.refcount = 0
        self.last_used = 0


class PrefixLease:
    """A pinned match: the node chain a running request borrows.

    ``block_ids`` are the pool blocks covering ``matched_tokens`` prompt
    tokens (``matched_tokens == len(block_ids) * block_size``).  The
    engine holds the lease for the request's whole slot residency and
    releases it on retirement; ``insert()`` extends it over any blocks
    newly cached from this request's prefill.

    In unified-pool mode a partial tail match rides along:
    ``tail_block`` is a cached pool block whose first ``tail_tokens``
    tokens extend the full-block match (``matched_tokens`` includes
    them); the engine serves it copy-on-write.  The tail node is pinned
    in ``nodes`` (so it survives until release) but its block is NOT in
    ``block_ids`` — it is never leased into a table directly."""

    __slots__ = ("nodes", "block_ids", "matched_tokens", "tail_block",
                 "tail_tokens")

    def __init__(self, nodes, block_size):
        self.nodes = list(nodes)
        self.block_ids = [n.block for n in self.nodes]
        self.matched_tokens = len(self.nodes) * block_size
        self.tail_block = None
        self.tail_tokens = 0


class PrefixCache:
    """Device-resident prefix-KV block pool + the radix store over it.

    ``budget_bytes`` bounds pool HBM use; a budget smaller than one
    block (or ``block_size=0`` upstream) degenerates to capacity 0 —
    every lookup misses, and the engine's prefill program still traces
    the same gather over the scratch-only pool, so enabling the cache
    never changes compiled-program structure.
    """

    def __init__(self, num_layers, block_size, kv_heads, head_dim,
                 dtype=jnp.float32, budget_bytes=0, pool=None,
                 bytes_per_block=None):
        self.num_layers = num_layers
        self.block_size = int(block_size)
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        itemsize = jnp.dtype(dtype).itemsize
        # the engine overrides bytes_per_block in unified-pool mode so
        # the byte budget caps pinned blocks at the pool's ACTUAL block
        # size (a quantized pool's blocks are ~4x smaller, so the same
        # budget pins ~4x more of them)
        self.bytes_per_block = bytes_per_block or (
            2 * num_layers * self.block_size
            * kv_heads * head_dim * itemsize)
        self.capacity = max(0, int(budget_bytes) // self.bytes_per_block) \
            if self.block_size else 0
        #: unified-pool mode: hold refcounted blocks of the engine's
        #: PagedKVPool instead of owning buffers (see module docstring)
        self.pool = pool
        self._held = 0               # pool blocks the radix store holds
        if pool is None:
            shape = (self.capacity + 1, max(1, self.block_size), kv_heads,
                     head_dim)
            self.pool_k = [jnp.zeros(shape, dtype)
                           for _ in range(num_layers)]
            self.pool_v = [jnp.zeros(shape, dtype)
                           for _ in range(num_layers)]
            self._free = list(range(self.capacity, 0, -1))  # 1..capacity
        else:
            self.pool_k = self.pool_v = None
            self._free = []
        self._root = _Node((), 0, None)
        self._clock = 0
        #: demotion hook (tiered KV): ``spill(path_tokens, block_id) ->
        #: bool`` is called by ``_evict`` with the victim's FULL token
        #: path and its still-live pool block BEFORE the block is
        #: released — a True return means the block's bytes now live in
        #: the host arena (dest="host"); False/None means the eviction
        #: is a real drop (dest="dropped").  The engine installs it;
        #: None keeps the pre-tier drop-on-evict behavior.
        self.spill = None
        #: batched demotion hook: ``spill_batch(paths, block_ids) ->
        #: [bool, ...]`` — the same contract as ``spill`` over a whole
        #: eviction pass at once, so a bulk ``reclaim()`` pays ONE
        #: device round-trip for all its victims instead of one per
        #: block.  Preferred over ``spill`` wherever it is installed.
        self.spill_batch = None
        #: metric label for the eviction counters (the engine's
        #: profiler name, so two engines stay distinguishable)
        self.metric_label = ""
        # counters (engine surfaces them through stats())
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evictions = 0
        self.evictions_demoted = 0
        self.evictions_dropped = 0
        self.inserted_blocks = 0

    # ------------------------------------------------------------ match
    def _walk(self, tokens, limit_tokens):
        """The matched node chain for ``tokens``, full blocks only,
        covering at most ``limit_tokens`` tokens."""
        bs = self.block_size
        chain = []
        if not bs or self.capacity == 0:
            return chain
        node = self._root
        max_blocks = limit_tokens // bs
        for i in range(max_blocks):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                break
            chain.append(child)
            node = child
        return chain

    def _cow_match(self, tokens, chain):
        """Unified-mode partial-tail match after the full-block walk:
        among the children of the last matched node, the one sharing the
        longest common token prefix with the rest of ``tokens``.
        Returns ``(node, m)`` with ``0 < m < block_size`` tokens usable
        copy-on-write, or ``(None, 0)``.  The cap at
        ``len(tokens) - 1 - matched`` keeps the one-token-to-prefill
        invariant, and also proves ``m < block_size``: a child matching
        a WHOLE in-cap block would have been matched by the walk."""
        if self.pool is None:
            return None, 0
        matched = len(chain) * self.block_size
        node = chain[-1] if chain else self._root
        rest = tokens[matched:]
        cap = len(tokens) - 1 - matched
        best, best_m = None, 0
        for child in node.children.values():
            m = 0
            for a, b in zip(child.tokens, rest):
                if a != b:
                    break
                m += 1
            m = min(m, cap)
            if m > best_m:
                best, best_m = child, m
        return best, best_m

    def lookup(self, tokens):
        """Matched-prefix length in tokens, side-effect free (used for
        admission bucketing; capped at ``len(tokens) - 1`` so a suffix
        of at least one token always remains to prefill).  In unified
        mode this includes the copy-on-write tail match."""
        chain = self._walk(tokens, len(tokens) - 1)
        _, m = self._cow_match(tokens, chain)
        return len(chain) * self.block_size + m

    def acquire(self, tokens):
        """Match + pin: refcount the matched chain and bump its LRU
        clock.  Returns the lease the engine holds until retirement.
        In unified mode a partial tail match is pinned too and exposed
        as ``lease.tail_block``/``tail_tokens`` for the engine's COW
        copy (the tail node sits in ``lease.nodes`` so it stays alive,
        but not in ``lease.block_ids`` — it is never leased into a
        block table directly)."""
        chain = self._walk(tokens, len(tokens) - 1)
        self._clock += 1
        for n in chain:
            n.refcount += 1
            n.last_used = self._clock
        lease = PrefixLease(chain, self.block_size)
        tail, m = self._cow_match(tokens, chain)
        if m > 0:
            tail.refcount += 1
            tail.last_used = self._clock
            lease.nodes.append(tail)
            lease.tail_block = tail.block
            lease.tail_tokens = m
            lease.matched_tokens += m
        self.hit_tokens += lease.matched_tokens
        self.miss_tokens += len(tokens) - lease.matched_tokens
        return lease

    def release(self, lease):
        """Unpin a lease (idempotent): the chain becomes evictable once
        no other slot borrows it."""
        for n in lease.nodes:
            if n.refcount > 0:
                n.refcount -= 1
        lease.nodes = []

    # ------------------------------------------------------------ insert
    def insert(self, tokens, lease):
        """Cache every full block of ``tokens`` not already stored.

        Walks the trie creating missing nodes; each new node allocates a
        pool block (evicting LRU unpinned leaves when the free list is
        dry) and is pinned into ``lease``.  Returns
        ``[(block_index, block_id), ...]`` for the NEW blocks — the
        engine copies those ``block_size``-token windows of the
        request's freshly prefilled slot row into the pool.  Stops at
        the first block it cannot allocate (deeper blocks would be
        unreachable anyway)."""
        if self.pool is not None:
            raise RuntimeError(
                "insert() is the standalone-pool path; unified-pool "
                "mode caches via adopt()")
        bs = self.block_size
        if not bs or self.capacity == 0:
            return []
        self._clock += 1
        node = self._root
        new = []
        for i in range(len(tokens) // bs):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                block = self._alloc_block()
                if block is None:
                    break
                child = _Node(key, block, node)
                node.children[key] = child
                child.refcount += 1
                lease.nodes.append(child)
                lease.block_ids.append(block)
                new.append((i, block))
                self.inserted_blocks += 1
            child.last_used = self._clock
            node = child
        return new

    # ------------------------------------------------------- unified pool
    def adopt(self, tokens, lease, block_of):
        """Unified-mode caching: take shared references on the slot's
        freshly written private blocks instead of copying anything.

        Called after a prefill dispatch.  ``block_of(i)`` maps full-block
        index ``i`` of ``tokens`` to the pool block the slot's table
        points at.  Blocks already cached are skipped (for ``i`` below
        the lease's full-block match that is guaranteed — those table
        entries ARE the cached blocks); missing ones get a new radix
        node holding ``pool.share(block)`` — including a COW tail copy,
        which after prefill is a complete valid block and lands as a
        sibling of its source.  New nodes are pinned into ``lease``.
        Stops when the byte budget is exhausted and nothing is
        evictable."""
        bs = self.block_size
        if self.pool is None:
            raise RuntimeError("adopt() requires unified-pool mode")
        if not bs or self.capacity == 0:
            return 0
        self._clock += 1
        node = self._root
        adopted = 0
        for i in range(len(tokens) // bs):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                if self._held >= self.capacity and self.reclaim(1) == 0:
                    break
                block = int(block_of(i))
                if block == 0:
                    break            # scratch: slot row ended early
                self.pool.share(block)
                self._held += 1
                child = _Node(key, block, node)
                node.children[key] = child
                child.refcount += 1
                lease.nodes.append(child)
                adopted += 1
                self.inserted_blocks += 1
            child.last_used = self._clock
            node = child
        return adopted

    def graft(self, tokens, index, block):
        """Unified-mode promotion (tiered KV swap-in): hang an
        engine-allocated pool block — freshly uploaded from the host
        arena — onto the radix tree at full-block ``index`` of
        ``tokens``.  Ownership of the block's reference TRANSFERS to
        the new node (the caller must have ``pool.alloc()``d it and
        must NOT release it on success).  The node's key is
        ``tokens[index*bs : (index+1)*bs]`` — shorter than a block for
        a partial tail, which only ever matches copy-on-write.  Returns
        False (caller keeps ownership) when the parent chain is missing
        — promotions must land in path order — or when the byte budget
        is exhausted and nothing is evictable."""
        bs = self.block_size
        if self.pool is None:
            raise RuntimeError("graft() requires unified-pool mode")
        if not bs or self.capacity == 0:
            return False
        node = self._root
        for i in range(index):
            node = node.children.get(
                tuple(tokens[i * bs:(i + 1) * bs]))
            if node is None:
                return False
        key = tuple(tokens[index * bs:(index + 1) * bs])
        if not key or key in node.children:
            return False
        if self._held >= self.capacity and self.reclaim(1) == 0:
            return False
        self._clock += 1
        self._held += 1
        child = _Node(key, int(block), node)
        node.children[key] = child
        child.last_used = self._clock
        self.inserted_blocks += 1
        return True

    def reclaim(self, n_blocks):
        """Evict up to ``n_blocks`` LRU unpinned leaves, returning their
        pool blocks to the engine's free list.  Returns how many were
        freed (0 when everything live is pinned).  Victims are detached
        first and demoted in ONE batched spill pass — bulk reclaims
        (admission evicting many blocks to fit a batch) pay a single
        device round-trip, not one per block — then released."""
        victims = []
        while len(victims) < n_blocks:
            victim = self._lru_evictable()
            if victim is None:
                break
            # detach now (so the victim's parent can become the next
            # eligible leaf) but defer spill + release: the blocks'
            # bytes must stay live for the batched copy below
            del victim.parent.children[victim.tokens]
            victims.append(victim)
        for node, demoted in zip(victims, self._spill_nodes(victims)):
            self._release_evicted(node, demoted)
        return len(victims)

    def _alloc_block(self):
        if self._free:
            return self._free.pop()
        victim = self._lru_evictable()
        if victim is None:
            return None
        self._evict(victim)
        return self._free.pop()

    def _lru_evictable(self):
        """Oldest unpinned leaf, or None.  Leaves only: interior nodes
        stay until their whole subtree ages out, keeping every cached
        chain reachable from the root."""
        best = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is self._root or node.children or node.refcount:
                continue
            if best is None or node.last_used < best.last_used:
                best = node
        return best

    def _node_path(self, node):
        """The full token path from the root through ``node`` — the key
        a demoted block re-matches under."""
        parts = []
        while node is not self._root:
            parts.append(node.tokens)
            node = node.parent
        out = ()
        for tokens in reversed(parts):
            out += tokens
        return out

    def _evict(self, node):
        del node.parent.children[node.tokens]
        self._release_evicted(node, self._spill_nodes([node])[0])

    def _spill_nodes(self, nodes):
        """Demote-instead-of-drop for a pass of detached victims: one
        bool per node, True when its bytes now live in the host tier.
        Must run BEFORE the victims' pool blocks are released (the
        spill callbacks device_get them).  Full-block victims go
        through ``spill_batch`` when installed — one device round-trip
        for the whole pass — else per-node ``spill``; partial-tail
        graft nodes (token key shorter than a block) are worth less
        than a full block and are dropped like before."""
        out = [False] * len(nodes)
        if self.pool is None or (self.spill is None
                                 and self.spill_batch is None):
            return out
        full = [i for i, n in enumerate(nodes)
                if len(n.tokens) == self.block_size]
        if not full:
            return out
        if self.spill_batch is not None:
            kept = self.spill_batch(
                [self._node_path(nodes[i]) for i in full],
                [nodes[i].block for i in full])
            for i, ok in zip(full, kept):
                out[i] = bool(ok)
        else:
            for i in full:
                out[i] = bool(self.spill(self._node_path(nodes[i]),
                                         nodes[i].block))
        return out

    def _release_evicted(self, node, demoted):
        """Return a detached victim's block and settle the eviction
        counters (any demotion already happened in ``_spill_nodes``)."""
        if self.pool is not None:
            self.pool.release(node.block)   # back to the engine free list
            self._held -= 1
        else:
            self._free.append(node.block)
        self.evictions += 1
        if demoted:
            self.evictions_demoted += 1
        else:
            self.evictions_dropped += 1
        _SRV_PREFIX_EVICT.inc(engine=self.metric_label,
                              dest="host" if demoted else "dropped")

    # ------------------------------------------------------------ device
    def rebind(self, new_k, new_v):
        """Adopt updated pool buffers returned by a jitted program."""
        self.pool_k = list(new_k)
        self.pool_v = list(new_v)

    # ------------------------------------------------------------ stats
    def _count_nodes(self):
        n, stack = 0, [self._root]
        while stack:
            node = stack.pop()
            n += len(node.children)
            stack.extend(node.children.values())
        return n

    def stats(self):
        total = self.hit_tokens + self.miss_tokens
        return {
            "block_size": self.block_size,
            "capacity_blocks": self.capacity,
            "used_blocks": self._held if self.pool is not None
            else self.capacity - len(self._free),
            "cached_nodes": self._count_nodes(),
            "hit_tokens": self.hit_tokens,
            "miss_tokens": self.miss_tokens,
            "hit_ratio": (self.hit_tokens / total) if total else 0.0,
            "evictions": self.evictions,
            "evictions_demoted": self.evictions_demoted,
            "evictions_dropped": self.evictions_dropped,
            "inserted_blocks": self.inserted_blocks,
        }
