"""Shared-prefix KV reuse: a block-granular radix store over prompt
token ids, mapping cached prefixes to device-resident KV blocks.

The serving regime the ROADMAP targets — heavy traffic from millions of
users — is dominated by prompts that share long prefixes (system
prompts, few-shot preambles, chat history).  Recomputing the KV for a
shared prefix on every admission wastes exactly the work this module
caches: SGLang's RadixAttention (Zheng et al., 2023) keeps reusable KV
in a radix tree over token ids, and vLLM (Kwon et al., 2023) stores KV
in fixed-size blocks so reuse needs no reshapes.  This module combines
both ideas TPU-native:

* **Block pool** — per layer, ONE preallocated
  ``[capacity + 1, block_size, kv_heads, head_dim]`` k/v buffer pair.
  A cached prefix is a chain of block ids into that pool, so "copy the
  cached prefix into a request's slot row" is a single gather the
  engine traces INTO its batched prefill program (no extra dispatch).
  Block 0 is a reserved scratch block: padding lanes gather/scatter it
  freely, and nothing semantic ever reads it.
* **Radix store** — a trie whose edges are full blocks of
  ``block_size`` token ids (the hash-on-block-tokens formulation of a
  radix tree: shared prefixes share nodes, block-granular splits).
  Matching walks full blocks only and is capped at ``len(prompt) - 1``
  tokens, so an exact-hit prompt still prefills at least its final
  token (the logits source for its first sampled token).
* **LRU eviction under a byte budget** — capacity is
  ``budget_bytes // bytes_per_block``; when the free list runs dry the
  least-recently-used *unpinned leaf* is evicted (leaves only, so every
  cached chain stays reachable from the root).
* **Refcounts** — ``acquire()`` pins the matched chain while a slot
  borrows it; pinned nodes are never evicted.  ``insert()`` extends the
  lease over newly cached blocks; ``release()`` unpins on retirement.

Everything here is host-side bookkeeping over small python dicts; the
only device state is the block pool, which the engine's compiled
programs gather from (prefill) and scatter into (post-prefill insert).
"""

from __future__ import annotations

import jax.numpy as jnp


class _Node:
    """One full-block edge of the radix store."""

    __slots__ = ("tokens", "block", "parent", "children", "refcount",
                 "last_used")

    def __init__(self, tokens, block, parent):
        self.tokens = tokens          # tuple of block_size token ids
        self.block = block            # pool block id (>= 1; 0 is scratch)
        self.parent = parent
        self.children = {}            # block-token tuple -> _Node
        self.refcount = 0
        self.last_used = 0


class PrefixLease:
    """A pinned match: the node chain a running request borrows.

    ``block_ids`` are the pool blocks covering ``matched_tokens`` prompt
    tokens (``matched_tokens == len(block_ids) * block_size``).  The
    engine holds the lease for the request's whole slot residency and
    releases it on retirement; ``insert()`` extends it over any blocks
    newly cached from this request's prefill."""

    __slots__ = ("nodes", "block_ids", "matched_tokens")

    def __init__(self, nodes, block_size):
        self.nodes = list(nodes)
        self.block_ids = [n.block for n in self.nodes]
        self.matched_tokens = len(self.nodes) * block_size


class PrefixCache:
    """Device-resident prefix-KV block pool + the radix store over it.

    ``budget_bytes`` bounds pool HBM use; a budget smaller than one
    block (or ``block_size=0`` upstream) degenerates to capacity 0 —
    every lookup misses, and the engine's prefill program still traces
    the same gather over the scratch-only pool, so enabling the cache
    never changes compiled-program structure.
    """

    def __init__(self, num_layers, block_size, kv_heads, head_dim,
                 dtype=jnp.float32, budget_bytes=0):
        self.num_layers = num_layers
        self.block_size = int(block_size)
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        itemsize = jnp.dtype(dtype).itemsize
        self.bytes_per_block = (2 * num_layers * self.block_size
                                * kv_heads * head_dim * itemsize)
        self.capacity = max(0, int(budget_bytes) // self.bytes_per_block) \
            if self.block_size else 0
        shape = (self.capacity + 1, max(1, self.block_size), kv_heads,
                 head_dim)
        self.pool_k = [jnp.zeros(shape, dtype) for _ in range(num_layers)]
        self.pool_v = [jnp.zeros(shape, dtype) for _ in range(num_layers)]
        self._free = list(range(self.capacity, 0, -1))   # ids 1..capacity
        self._root = _Node((), 0, None)
        self._clock = 0
        # counters (engine surfaces them through stats())
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evictions = 0
        self.inserted_blocks = 0

    # ------------------------------------------------------------ match
    def _walk(self, tokens, limit_tokens):
        """The matched node chain for ``tokens``, full blocks only,
        covering at most ``limit_tokens`` tokens."""
        bs = self.block_size
        chain = []
        if not bs or self.capacity == 0:
            return chain
        node = self._root
        max_blocks = limit_tokens // bs
        for i in range(max_blocks):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                break
            chain.append(child)
            node = child
        return chain

    def lookup(self, tokens):
        """Matched-prefix length in tokens, side-effect free (used for
        admission bucketing; capped at ``len(tokens) - 1`` so a suffix
        of at least one token always remains to prefill)."""
        return len(self._walk(tokens, len(tokens) - 1)) * self.block_size

    def acquire(self, tokens):
        """Match + pin: refcount the matched chain and bump its LRU
        clock.  Returns the lease the engine holds until retirement."""
        chain = self._walk(tokens, len(tokens) - 1)
        self._clock += 1
        for n in chain:
            n.refcount += 1
            n.last_used = self._clock
        lease = PrefixLease(chain, self.block_size)
        self.hit_tokens += lease.matched_tokens
        self.miss_tokens += len(tokens) - lease.matched_tokens
        return lease

    def release(self, lease):
        """Unpin a lease (idempotent): the chain becomes evictable once
        no other slot borrows it."""
        for n in lease.nodes:
            if n.refcount > 0:
                n.refcount -= 1
        lease.nodes = []

    # ------------------------------------------------------------ insert
    def insert(self, tokens, lease):
        """Cache every full block of ``tokens`` not already stored.

        Walks the trie creating missing nodes; each new node allocates a
        pool block (evicting LRU unpinned leaves when the free list is
        dry) and is pinned into ``lease``.  Returns
        ``[(block_index, block_id), ...]`` for the NEW blocks — the
        engine copies those ``block_size``-token windows of the
        request's freshly prefilled slot row into the pool.  Stops at
        the first block it cannot allocate (deeper blocks would be
        unreachable anyway)."""
        bs = self.block_size
        if not bs or self.capacity == 0:
            return []
        self._clock += 1
        node = self._root
        new = []
        for i in range(len(tokens) // bs):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                block = self._alloc_block()
                if block is None:
                    break
                child = _Node(key, block, node)
                node.children[key] = child
                child.refcount += 1
                lease.nodes.append(child)
                lease.block_ids.append(block)
                new.append((i, block))
                self.inserted_blocks += 1
            child.last_used = self._clock
            node = child
        return new

    def _alloc_block(self):
        if self._free:
            return self._free.pop()
        victim = self._lru_evictable()
        if victim is None:
            return None
        self._evict(victim)
        return self._free.pop()

    def _lru_evictable(self):
        """Oldest unpinned leaf, or None.  Leaves only: interior nodes
        stay until their whole subtree ages out, keeping every cached
        chain reachable from the root."""
        best = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is self._root or node.children or node.refcount:
                continue
            if best is None or node.last_used < best.last_used:
                best = node
        return best

    def _evict(self, node):
        del node.parent.children[node.tokens]
        self._free.append(node.block)
        self.evictions += 1

    # ------------------------------------------------------------ device
    def rebind(self, new_k, new_v):
        """Adopt updated pool buffers returned by a jitted program."""
        self.pool_k = list(new_k)
        self.pool_v = list(new_v)

    # ------------------------------------------------------------ stats
    def _count_nodes(self):
        n, stack = 0, [self._root]
        while stack:
            node = stack.pop()
            n += len(node.children)
            stack.extend(node.children.values())
        return n

    def stats(self):
        total = self.hit_tokens + self.miss_tokens
        return {
            "block_size": self.block_size,
            "capacity_blocks": self.capacity,
            "used_blocks": self.capacity - len(self._free),
            "cached_nodes": self._count_nodes(),
            "hit_tokens": self.hit_tokens,
            "miss_tokens": self.miss_tokens,
            "hit_ratio": (self.hit_tokens / total) if total else 0.0,
            "evictions": self.evictions,
            "inserted_blocks": self.inserted_blocks,
        }
