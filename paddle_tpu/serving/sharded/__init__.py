"""Tensor-parallel sharded serving: one engine per mesh, not per chip.

``MeshEngine`` wraps the single-chip :class:`~..engine.Engine` with a
``shard_map``-compiled forward over a ``("dp", "tp")`` mesh under the
:class:`ServingSpecLayout` placement discipline — scheduler, prefix
cache, preemption, speculative decoding and the quant knobs ride along
unmodified, and the output is bitwise-equal to the single-chip engine
(see docs/PARITY.md N19g).
"""

from .layout import ServingSpecLayout
from .mesh_engine import MeshEngine

__all__ = ["MeshEngine", "ServingSpecLayout"]
