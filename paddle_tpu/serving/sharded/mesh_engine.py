"""MeshEngine: the tensor-parallel serving engine — one engine per mesh.

The entire single-chip ``Engine`` rides along unmodified: scheduler,
prefix radix store, preemption, horizon scan, speculative decoding,
sampling, host-authoritative mirrors, observability.  The ONLY override
is ``_run_model`` — the functionalized forward every compiled program
(prefill, horizon-scan body, verify window) calls — which here runs a
``shard_map`` over a ``("dp","tp")`` mesh with the
:class:`~.layout.ServingSpecLayout` placements.

Bitwise-parity doctrine (validated against the single-chip jitted
forward for MHA and GQA, prefill and decode shapes):

* every Linear is **column-parallel** (output dimension sharded over
  tp) — each output element is a full-length contraction identical to
  the single-chip one.  Row-parallel partial-sum matmuls are banned:
  psum over partial products re-associates float adds and parity dies;
* each shard runs rope + ``paged_write`` + the ragged paged-attention
  XLA fallback on its LOCAL head slice (all three are per-head/per-
  element exact, so a head slice computes bitwise what the full-head
  program computes for those heads);
* head outputs combine through **ONE psum per layer** over zero-padded
  disjoint supports: each shard ``dynamic_update_slice``s its local
  heads into zeros[b,s,heads,head_dim] at its head offset; psum of
  disjoint supports is exact because ``x + 0.0 == x`` bitwise;
* every other combine is ``lax.all_gather(tiled=True)`` — a pure
  concatenation in shard order, which moves bytes, never re-rounds.

Decode-program collective census (hand-derived, gated EXACT by
check-bench against MULTICHIP_BENCH.json): per layer per scanned step,
1 psum (head combine) + 3 all_gathers (o_proj out, SwiGLU intermediate,
down_proj out), plus 1 all_gather per step for the lm_head logits — so
a horizon-``h`` dispatch over ``L`` layers counts ``psum@tp = L*h`` and
``all_gather@tp = (3L+1)*h`` (int8 KV adds ``pmax@tp = 2L*h`` for the
cross-shard absmax in ``paged_write_quant``).

Parity must be compared jit-vs-jit: eager and jitted XLA execution
round differently (fusion), and the engine's CompiledFn jits every
program — which is the production path.

Deliberately NOT built here (see ROADMAP): dp > 1 (reserved for
disaggregated prefill/decode), multi-host meshes, and the Pallas decode
kernel under shard_map (the per-shard path uses the XLA fallback; on
TPU the kernel would slot in per-shard the same way).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...core import tape as _tape
from ...core.tensor import Tensor
from ...distributed.shard_map_compat import NO_CHECK, shard_map
from ...nn import functional as F
from ...ops.rope import apply_rotary_emb
from ...tensor import manipulation as M
from ..engine import Engine
from ..kv_cache import PagedKV, paged_write, paged_write_quant
from ..paged_attention import paged_attention
from .layout import ServingSpecLayout


class MeshEngine(Engine):
    """Tensor-parallel :class:`~..engine.Engine` over a ``(dp, tp)``
    device mesh.  Construct with ``tp=N`` (or ``mesh_shape=(1, N)``);
    tp must divide the model's kv_heads/heads/hidden/intermediate/vocab
    (validated eagerly by :class:`ServingSpecLayout`).  ``tp=1`` is the
    degenerate single-shard mesh — useful as the parity control.

    Give each CONCURRENTLY-driven engine its own model instance: every
    engine traces through ``model.use_state()``, and a mesh engine
    swaps in locally-SLICED weights — sharing one module object with
    another engine stepping on a different thread (e.g. gateway
    replicas) races the swap.  Between same-shape single-chip engines
    the race is value-benign; against a mesh engine it is a shape
    error mid-trace."""

    def __init__(self, model, config=None, mesh_shape=None, tp=None,
                 register_profiler=True, layout=None):
        self.mesh_shape = self._norm_mesh_knob(mesh_shape, tp)
        dp, tp_size = self.mesh_shape
        self.tp = tp_size
        self.layout = layout or ServingSpecLayout()
        self.layout.validate(model.config, tp_size)
        devices = jax.devices()
        need = dp * tp_size
        if need > len(devices):
            raise ValueError(
                f"mesh_shape {self.mesh_shape} needs {need} devices, "
                f"only {len(devices)} visible (CPU runs need "
                f"--xla_force_host_platform_device_count)")
        self.mesh = Mesh(np.array(devices[:need]).reshape(dp, tp_size),
                         self.layout.mesh_axes)
        super().__init__(model, config, register_profiler=register_profiler)
        self._shard_placement()
        self._build_forward()

    # ------------------------------------------------------------- knobs
    @staticmethod
    def _norm_mesh_knob(mesh_shape, tp):
        """Normalize the (mesh_shape, tp) knob pair to a ``(dp, tp)``
        tuple, mirroring ``Engine._norm_quant_knob``'s loud-on-nonsense
        discipline."""
        if mesh_shape is None and tp is None:
            raise ValueError(
                "MeshEngine needs mesh_shape=(dp, tp) or tp=<int>")
        if mesh_shape is None:
            mesh_shape = (1, tp)
        try:
            shape = tuple(int(v) for v in mesh_shape)
        except (TypeError, ValueError):
            raise ValueError(
                f"unsupported mesh_shape {mesh_shape!r} "
                "(expected a (dp, tp) pair of ints)")
        if len(shape) != 2:
            raise ValueError(
                f"unsupported mesh_shape {mesh_shape!r} "
                "(expected exactly (dp, tp))")
        dp, tp_size = shape
        if tp is not None and int(tp) != tp_size:
            raise ValueError(
                f"tp={tp} contradicts mesh_shape {mesh_shape!r}")
        if tp_size < 1:
            raise ValueError(f"tp must be >= 1, got {tp_size}")
        if dp != 1:
            raise ValueError(
                f"dp={dp} is not supported yet: the dp axis is reserved "
                "for disaggregated prefill/decode (ROADMAP); use "
                "mesh_shape=(1, tp)")
        return shape

    # --------------------------------------------------------- placement
    def _put(self, arr, spec):
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def _shard_placement(self):
        """Device_put weights and the paged pool under the layout's
        NamedShardings.  Weight-quant pairs shard BOTH leaves along the
        output axis — ``channelwise_scales`` are per OUTPUT channel
        ([1, out]), so slicing q and scale together commutes bitwise
        with dequantization.  Replicated inputs (ids/tables/scan state)
        need no placement: uncommitted host uploads replicate onto the
        mesh under jit."""
        specs = self.layout.state_specs(self._state_names)
        arrays = []
        for a, sp in zip(self._state_arrays, specs):
            if type(a) is tuple:
                arrays.append(tuple(self._put(x, sp) for x in a))
            else:
                arrays.append(self._put(a, sp))
        self._state_arrays = arrays
        self._place_pool()

    def _place_pool(self):
        """(Re-)place the paged pool arrays under the layout's
        shardings.  Beyond construction this is the tiered-KV swap-in
        hook: a host-arena upload rebinds pool buffers whose sharding
        XLA inferred, and re-putting them restores the head-sharded
        placement before the next dispatch.  The swap itself is pure
        byte movement — the host arena holds GATHERED full blocks
        (device_get assembles shards on the way out), so placement is
        the only sharded-serving concern; per-shard local-slice arenas
        are deliberately NOT built (see ARCHITECTURE \"Tiered KV\")."""
        pool_spec = self.layout.kv_pool()
        self.pool.k = [self._put(a, pool_spec) for a in self.pool.k]
        self.pool.v = [self._put(a, pool_spec) for a in self.pool.v]
        if self._kv_quant:
            sc = self.layout.kv_scales()
            self.pool.k_scale = [self._put(a, sc)
                                 for a in self.pool.k_scale]
            self.pool.v_scale = [self._put(a, sc)
                                 for a in self.pool.v_scale]

    # ----------------------------------------------------- mesh forward
    def _build_forward(self):
        """Build the shard_map-wrapped per-shard forward once — it is
        shape-polymorphic (prefill buckets, decode windows, and nb
        re-buckets all trace through the same callable; jit caching
        stays at the CompiledFn layer)."""
        num_layers = len(self.model.model.layers)
        pool_spec = self.layout.kv_pool()
        state_specs = tuple(
            (sp, sp) if type(a) is tuple else sp
            for a, sp in zip(self._state_arrays,
                             self.layout.state_specs(self._state_names)))
        in_specs = [state_specs, P(), P(), P(),
                    (pool_spec,) * num_layers, (pool_spec,) * num_layers]
        out_specs = [P(), (pool_spec,) * num_layers,
                     (pool_spec,) * num_layers]
        if self._kv_quant:
            sc = self.layout.kv_scales()
            in_specs += [(sc,) * num_layers, (sc,) * num_layers]
            out_specs += [(sc,) * num_layers, (sc,) * num_layers]
        self._mesh_fwd = shard_map(
            self._shard_forward, mesh=self.mesh,
            in_specs=tuple(in_specs), out_specs=tuple(out_specs),
            **NO_CHECK)

    def _shard_forward(self, state, ids, tables, pos, pool_k, pool_v,
                       pool_ks=None, pool_vs=None):
        """The per-shard decode-model forward (runs inside shard_map,
        once per tp rank).  Mirrors ``GPTModel`` + ``_forward_paged``
        with the layout's tp combines spliced in; sublayers are bound to
        their LOCAL weight slices through ``use_state`` (which swaps raw
        arrays without shape checks)."""
        axis = self.layout.tp_axis
        ti = lax.axis_index(axis)
        arrays = {}
        for name, a in zip(self._state_names, state):
            if type(a) is tuple:
                q, scale = a
                a = (q.astype(jnp.float32)
                     * scale).astype(self._wq_dtypes[name])
            arrays[name] = a
        mdl = self.model.model
        cfg = self.model.config
        heads, kvh, hd = (cfg.num_attention_heads, cfg.kv_heads,
                          cfg.head_dim)
        heads_l, kvh_l = heads // self.tp, kvh // self.tp
        b, s = ids.shape
        quant = pool_ks is not None

        def gather(t):
            # tiled all_gather on the last axis: exact concatenation in
            # shard order — the column-parallel combine
            return Tensor(lax.all_gather(t._data, axis,
                                         axis=t._data.ndim - 1,
                                         tiled=True))

        new_k, new_v, new_ks, new_vs = [], [], [], []
        with _tape.no_grad(), self.model.use_state(arrays):
            x = mdl.embed_tokens(Tensor(ids))
            pos_ids = Tensor(pos[:, None]
                             + jnp.arange(s, dtype=pos.dtype)[None, :])
            for i, layer in enumerate(mdl.layers):
                attn = layer.self_attn
                residual = x
                h = layer.input_layernorm(x)
                q = M.reshape(attn.q_proj(h), [b, s, heads_l, hd])
                k = M.reshape(attn.k_proj(h), [b, s, kvh_l, hd])
                v = M.reshape(attn.v_proj(h), [b, s, kvh_l, hd])
                q = apply_rotary_emb(q, position_ids=pos_ids,
                                     base=attn.rope_theta)
                k = apply_rotary_emb(k, position_ids=pos_ids,
                                     base=attn.rope_theta)
                if quant:
                    kp, ks = paged_write_quant(pool_k[i], pool_ks[i],
                                               k._data, tables, pos,
                                               axis_name=axis)
                    vp, vs = paged_write_quant(pool_v[i], pool_vs[i],
                                               v._data, tables, pos,
                                               axis_name=axis)
                    new_ks.append(ks)
                    new_vs.append(vs)
                else:
                    kp = paged_write(pool_k[i], k._data, tables, pos)
                    vp = paged_write(pool_v[i], v._data, tables, pos)
                    ks = vs = None
                new_k.append(kp)
                new_v.append(vp)
                out = paged_attention(q._data, kp, vp, tables, pos,
                                      ks, vs)
                # ONE psum per layer: each shard owns a disjoint head
                # range, so summing zero-padded buffers is exact
                full = jnp.zeros((b, s, heads, hd), out.dtype)
                full = lax.dynamic_update_slice(
                    full, out, (0, 0, ti * heads_l, 0))
                full = lax.psum(full, axis)
                o = attn.o_proj(M.reshape(Tensor(full),
                                          [b, s, heads * hd]))
                x = residual + layer.dropout(gather(o))
                residual = x
                h2 = layer.post_attention_layernorm(x)
                g = gather(F.silu(layer.mlp.gate_proj(h2))
                           * layer.mlp.up_proj(h2))
                d = gather(layer.mlp.down_proj(g))
                x = residual + layer.dropout(d)
            x = mdl.norm(x)
            logits = gather(self.model.lm_head(x))
        if quant:
            return (logits._data, tuple(new_k), tuple(new_v),
                    tuple(new_ks), tuple(new_vs))
        return logits._data, tuple(new_k), tuple(new_v)

    def _run_model(self, state_arrays, ids, views):
        """The single override point: same contract as the base
        ``_run_model`` (raw param arrays + ids + PagedKV views ->
        (logits, new views)), routed through the mesh forward.  Every
        caller — prefill, the horizon-scan body, spec-decode verify
        windows — inherits sharding with no code of its own."""
        num_layers = len(views)
        tables, pos = views[0].tables, views[0].pos
        pool_k = tuple(v.k for v in views)
        pool_v = tuple(v.v for v in views)
        if self._kv_quant:
            pool_ks = tuple(v.k_scale for v in views)
            pool_vs = tuple(v.v_scale for v in views)
            logits, nk, nv, nks, nvs = self._mesh_fwd(
                tuple(state_arrays), ids, tables, pos, pool_k, pool_v,
                pool_ks, pool_vs)
        else:
            logits, nk, nv = self._mesh_fwd(
                tuple(state_arrays), ids, tables, pos, pool_k, pool_v)
            nks = nvs = (None,) * num_layers
        s = ids.shape[1]
        new_views = [PagedKV(k, v, tables, pos + s, ks, vs)
                     for k, v, ks, vs in zip(nk, nv, nks, nvs)]
        return logits, new_views

    # ------------------------------------------------------------ census
    def expected_decode_census(self, horizon=None, k_draft=0):
        """The hand-derived collective census of one compiled decode
        dispatch — the contract MULTICHIP_BENCH.json gates EXACT.  Per
        scanned step: L psums (head combines) + 3L+1 all_gathers
        (o_proj, SwiGLU intermediate, down_proj per layer; lm_head
        once); int8 KV adds 2L pmaxes (k and v absmax per layer)."""
        h = int(horizon or self.config.max_horizon)
        num_layers = len(self.model.model.layers)
        axis = self.layout.tp_axis
        census = {("psum", axis): num_layers * h,
                  ("all_gather", axis): (3 * num_layers + 1) * h}
        if self._kv_quant:
            census[("pmax", axis)] = 2 * num_layers * h
        return census

    def decode_census_program(self, horizon=None, k_draft=0, nb=2):
        """(fn, args) for the comms walker / bench: the REAL compiled
        decode program (``_decode_fn`` with static horizon/k baked)
        over representative zero-state arguments at table width
        ``nb``."""
        h = int(horizon or self.config.max_horizon)
        n = self.config.num_slots
        nb = int(min(nb, self.cache.max_blocks_per_slot))
        i32, f32 = jnp.int32, jnp.float32
        pool_ks = list(self.pool.k_scale) if self._kv_quant else None
        pool_vs = list(self.pool.v_scale) if self._kv_quant else None
        args = (self._state_arrays,
                jnp.zeros(n, i32), jnp.zeros(n, i32), jnp.zeros(n, i32),
                jnp.ones(n, bool),
                jnp.zeros((n, self.config.max_seq_len), i32),
                jnp.ones(n, bool), jnp.zeros(n, jnp.uint32),
                jnp.zeros(n, f32), jnp.zeros(n, i32), jnp.ones(n, f32),
                jnp.full(n, -1, i32),
                jnp.full(n, self.config.max_seq_len, i32),
                jnp.zeros((n, nb), i32),
                list(self.pool.k), list(self.pool.v), pool_ks, pool_vs)
        # grammar args ride as keywords (positional would land on the
        # horizon/k_draft slots already bound above); Nones with
        # structured generation off, slab tables + sentinel states on
        dfa_state, dfa_next, dfa_mask, dfa_forced = \
            self._grammar_program_args()
        fn = functools.partial(self._decode_fn, horizon=h,
                               k_draft=int(k_draft),
                               dfa_state=dfa_state, dfa_next=dfa_next,
                               dfa_mask=dfa_mask, dfa_forced=dfa_forced)
        return fn, args

    def decode_comms_report(self, horizon=None, k_draft=0, publish=False):
        """Walk the decode program's jaxpr with the PR 11 comms walker,
        assert it matches the hand census, and return the CommsReport
        (per-op counts + analytic wire bytes).  ``publish=True`` also
        lands the counts on the typed metrics registry — the serving
        programs' comms card."""
        from ...observability import comms

        fn, args = self.decode_census_program(horizon, k_draft)
        report = comms.analyze_fn(fn, *args)
        expected = self.expected_decode_census(horizon, k_draft)
        got = report.counts()
        if got != expected:
            raise AssertionError(
                f"decode census {got} != hand-derived {expected}")
        if publish:
            report.publish()
        return report

    # ------------------------------------------------------------- stats
    def stats(self):
        """Base engine stats plus the mesh stamp: shape, devices, and
        the per-shard slice of the KV pool (each chip holds only
        kv_heads/tp of every block)."""
        s = super().stats()
        s["mesh"] = {
            "mesh_shape": {"dp": self.mesh_shape[0],
                           "tp": self.mesh_shape[1]},
            "axes": list(self.layout.mesh_axes),
            "devices": [str(d) for d in self.mesh.devices.flat],
            "kv_pool_bytes_per_shard":
                self._kv_pool_bytes() // self.tp,
            "kv_heads_per_shard":
                self.model.config.kv_heads // self.tp,
        }
        return s
