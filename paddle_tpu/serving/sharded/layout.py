"""ServingSpecLayout: the PartitionSpec discipline for mesh-sharded
serving (one engine per mesh, not per chip).

Modeled on the SpecLayout idiom (SNIPPETS.md [2]): a frozen dataclass of
named axes whose methods return the canonical PartitionSpec for each
parameter/state family, plus a name-based heuristic that maps every
decode-model parameter to its spec.  The layout here differs from a
training SpecLayout in one decisive way: **every Linear is sharded on
its OUTPUT dimension** (column-parallel), including the projections a
Megatron layout would make row-parallel (o_proj, down_proj).

Why: row-parallel splits the matmul's CONTRACTION dimension, so each
shard holds a partial sum and the combining psum re-associates float
adds — bitwise parity with the single-chip engine dies there.  Column-
parallel keeps every output element a full-length contraction identical
to the single-chip one; shards are combined by concatenation
(``lax.all_gather(tiled=True)``), which moves bytes but never re-rounds
a value, and attention head outputs combine through ONE psum per layer
over zero-padded disjoint supports (``x + 0.0 == x`` bitwise).  See
``mesh_engine.MeshEngine`` for the forward that consumes these specs.

The mesh is ``("dp", "tp")``; dp is fixed at 1 (reserved for the
disaggregated prefill/decode follow-up, see ROADMAP) and tp shards:

==========================  =======================  ====================
family                      spec                     note
==========================  =======================  ====================
q/k/v projections           P(None, "tp")            heads split over tp
o_proj / down_proj          P(None, "tp")            column-parallel (see
                                                     above, NOT Megatron
                                                     row-parallel)
gate/up projections         P(None, "tp")            SwiGLU split over tp
lm_head                     P(None, "tp")            vocab split over tp
embeddings / norms          P()                      replicated
paged KV pool               P(None, None, "tp", -)   kv_heads split: each
                                                     chip's block pool
                                                     holds its head slice
KV quant scales             P()                      per-token (head-free)
block tables / scan state   P()                      replicated; host
                                                     mirrors unchanged
==========================  =======================  ====================
"""

from __future__ import annotations

from dataclasses import dataclass

from jax.sharding import PartitionSpec as P

#: substrings naming the column-parallel (output-sharded) projections
_TP_SHARDED = ("q_proj", "k_proj", "v_proj", "o_proj",
               "gate_proj", "up_proj", "down_proj", "lm_head")


@dataclass(frozen=True)
class ServingSpecLayout:
    """Canonical PartitionSpecs for the sharded serving engine."""

    dp_axis: str = "dp"
    tp_axis: str = "tp"

    @property
    def mesh_axes(self):
        return (self.dp_axis, self.tp_axis)

    # ------------------------------------------------------- parameters
    def qkv_projection(self):
        """q/k/v weights [hidden, heads*head_dim]: heads split over tp."""
        return P(None, self.tp_axis)

    def attn_output(self):
        """o_proj [heads*head_dim, hidden]: OUTPUT-sharded (column-
        parallel), not Megatron row-parallel — see the module docstring."""
        return P(None, self.tp_axis)

    def ffn(self):
        """gate/up/down weights: output dimension split over tp."""
        return P(None, self.tp_axis)

    def lm_head(self):
        """lm_head [hidden, vocab]: vocab split over tp."""
        return P(None, self.tp_axis)

    def embedding(self):
        """Embedding tables replicated (serving reads one row per token;
        the capacity lever is the KV pool, not the embedding)."""
        return P()

    def norm(self):
        return P()

    # ----------------------------------------------------- engine state
    def kv_pool(self):
        """Paged pool [num_blocks, block_size, kv_heads, head_dim]: each
        chip's block pool holds only its KV-head slice."""
        return P(None, None, self.tp_axis, None)

    def kv_scales(self):
        """Quantized-pool per-token scales [num_blocks, block_size]:
        head-free, so replicated (each shard computes the identical
        full-head absmax via pmax — see kv_cache.paged_write_quant)."""
        return P()

    def engine_state(self):
        """Block tables and horizon-scan state (tokens/pos/counts/...):
        replicated; the host-authoritative mirrors are unchanged."""
        return P()

    def dfa_tables(self):
        """Structured-generation slab tables (transitions, legality
        bitmask, forced tokens) and the per-lane DFA state column:
        replicated — every chip masks its own vocab shard's logits from
        the same table, and the state walk is lane-indexed host logic,
        not a sharded tensor op."""
        return self.engine_state()

    # ------------------------------------------------------- name rules
    def parameter_spec(self, name):
        """Heuristic spec from a state_dict parameter name."""
        n = name.lower()
        if not n.endswith(".weight"):
            return self.engine_state()
        if any(p in n for p in ("q_proj", "k_proj", "v_proj")):
            return self.qkv_projection()
        if "o_proj" in n:
            return self.attn_output()
        if any(p in n for p in ("gate_proj", "up_proj", "down_proj")):
            return self.ffn()
        if "lm_head" in n:
            return self.lm_head()
        if "embed" in n:
            return self.embedding()
        return self.norm()

    def state_specs(self, names):
        """One spec per state_dict entry, in order."""
        return tuple(self.parameter_spec(n) for n in names)

    def is_tp_sharded(self, name):
        return (name.endswith(".weight")
                and any(p in name for p in _TP_SHARDED))

    # -------------------------------------------------------- validation
    def validate(self, model_config, tp):
        """Eagerly reject shapes the layout cannot shard: every tp-split
        dimension must divide evenly (a ragged shard would silently
        change which head/channel lives where), and tied embeddings have
        no lm_head weight to shard."""
        c = model_config
        tp = int(tp)
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if getattr(c, "tie_word_embeddings", False):
            raise ValueError(
                "sharded serving requires an untied lm_head "
                "(tie_word_embeddings=True has no lm_head weight to "
                "shard over tp)")
        checks = (
            ("num_key_value_heads (kv_heads)", c.kv_heads),
            ("num_attention_heads", c.num_attention_heads),
            ("hidden_size", c.hidden_size),
            ("intermediate_size", c.intermediate_size),
            ("vocab_size", c.vocab_size),
        )
        bad = [f"{name}={v}" for name, v in checks if v % tp != 0]
        if bad:
            raise ValueError(
                f"model not shardable over tp={tp}: "
                f"{', '.join(bad)} not divisible by tp")
        return True
