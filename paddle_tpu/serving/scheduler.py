"""Continuous-batching scheduler: admission queue + slot lifecycle.

Requests enter a FIFO queue on ``submit()`` and join the running batch
only at decode-step boundaries (the engine admits before each fused
step).  A request holds its slot until it finishes — EOS or max-tokens —
then the slot returns to the free list and the next queued request can
claim it.  All of this is host-side bookkeeping over the static-shape
device state; nothing here retraces anything.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from .sampling import SamplingParams

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"

FINISH_EOS = "eos"
FINISH_LENGTH = "length"


@dataclass
class Request:
    """One generation request and its full lifecycle state."""

    request_id: int
    prompt_ids: list
    sampling: SamplingParams
    status: str = WAITING
    slot: int | None = None
    output_ids: list = field(default_factory=list)
    finish_reason: str | None = None
    submit_time: float = field(default_factory=time.time)
    first_token_time: float | None = None

    @property
    def prompt_len(self):
        return len(self.prompt_ids)

    @property
    def n_generated(self):
        return len(self.output_ids)

    @property
    def remaining_budget(self):
        """Decode steps left before length retirement.  The engine's
        adaptive horizon never exceeds the smallest remaining budget of
        any running request, so a horizon dispatch cannot overrun a
        lane's ``max_new_tokens`` limit."""
        return self.sampling.max_new_tokens - self.n_generated

    @property
    def ttft(self):
        """Time-to-first-token in seconds (None until the first token)."""
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    def record_token(self, token):
        """Append a sampled token; returns True when the request is done
        (EOS or max_new_tokens reached)."""
        if self.first_token_time is None:
            self.first_token_time = time.time()
        self.output_ids.append(int(token))
        eos = self.sampling.eos_token_id
        if eos is not None and int(token) == int(eos):
            self.finish_reason = FINISH_EOS
            return True
        if self.n_generated >= self.sampling.max_new_tokens:
            self.finish_reason = FINISH_LENGTH
            return True
        return False


class Scheduler:
    """FIFO admission over a fixed slot pool."""

    def __init__(self, num_slots):
        self.num_slots = num_slots
        self.queue = deque()
        self.running = {}           # slot -> Request
        self._next_id = 0

    def submit(self, prompt_ids, sampling):
        req = Request(self._next_id, list(prompt_ids),
                      sampling.validate())
        self._next_id += 1
        self.queue.append(req)
        return req

    def admissible(self, free_slots):
        """Pop up to free_slots queued requests (join happens at the next
        decode-step boundary)."""
        out = []
        while self.queue and len(out) < free_slots:
            out.append(self.queue.popleft())
        return out

    def start(self, req, slot):
        req.status = RUNNING
        req.slot = slot
        self.running[slot] = req

    def finish(self, req):
        req.status = FINISHED
        del self.running[req.slot]

    @property
    def queue_depth(self):
        return len(self.queue)

    @property
    def has_work(self):
        return bool(self.queue or self.running)
