"""Continuous-batching scheduler: admission queue + slot lifecycle.

Requests enter a FIFO queue on ``submit()`` and join the running batch
only at decode-step boundaries (the engine admits before each fused
step).  A request holds its slot until it finishes — EOS or max-tokens —
then the slot returns to the free list and the next queued request can
claim it.  All of this is host-side bookkeeping over the static-shape
device state; nothing here retraces anything.

Admission is batch-aware: ``pop_batch()`` returns a group of queued
requests that share one prefill bucket so the engine can prefill them
all in ONE compiled dispatch.  Grouping may admit a later-submitted
same-bucket request ahead of an earlier different-bucket one, but only
inside a bounded **reorder window**: the queue head always anchors the
batch (strict no-head-starvation), and no request is ever overtaken by
more than ``reorder_window`` later-submitted requests in total.

Admission is also priority-aware (the gateway's admission layer):
every request carries an integer ``priority`` (default 0) and the
reorder window generalizes into a per-pair **overtake budget** —
request ``o`` may be admitted ahead of an earlier-submitted request
``s`` only while

    ``s.bypassed < reorder_window * (1 + max(0, o.priority - s.priority))``

so same-priority traffic keeps the original window exactly, a
higher-priority request gets a budget that widens linearly with the
priority gap, and the starvation bound stays hard: with priorities
capped at ``P``, a queued request is overtaken by at most
``reorder_window * (1 + P)`` later-submitted requests before it MUST
anchor the next batch.  A bounded stable promotion pass
(:meth:`Scheduler.promote`) bubbles higher-priority requests toward
the head inside that budget before each ``pop_batch``.

The exception is the **offline batch lane**: a request with
``priority < 0`` opts out of the starvation bound entirely —
interactive traffic (``priority >= 0``) overtakes it WITHOUT bound
(:meth:`Scheduler.overtake_cap` returns infinity against it, and a
skipped batch request never seals the ``pop_batch`` scan).  Batch
requests still run FIFO among themselves, still anchor a batch when
they reach the head of an otherwise-idle queue, and are first in line
for load shedding (:meth:`shed_victims` drops lowest priority first),
so the lane is preemptible capacity filler, not a starvation hazard.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from .sampling import SamplingParams

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"

FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_ABORT = "abort"


@dataclass
class Request:
    """One generation request and its full lifecycle state."""

    request_id: int
    prompt_ids: list
    sampling: SamplingParams
    status: str = WAITING
    slot: int | None = None
    output_ids: list = field(default_factory=list)
    finish_reason: str | None = None
    #: wall time of submit() — the TTFT clock starts HERE, so queue wait
    #: and prefill are both inside a request's time-to-first-token
    submit_time: float = field(default_factory=time.time)
    #: wall time admission claimed a slot (prefill start)
    admit_time: float | None = None
    first_token_time: float | None = None
    #: tokens of this prompt served from the prefix cache (set by the
    #: engine at admission; 0 when the cache is off or missed)
    prefix_hit_tokens: int = 0
    #: how many later-submitted requests were admitted ahead of this one
    #: (bounded by the scheduler's reorder window)
    bypassed: int = 0
    #: True while this request waits for RE-admission after preemption:
    #: it already held a slot and was swapped out, so admitting it ahead
    #: of later-submitted requests restores order rather than overtakes
    #: — pop_batch extends the head-anchor exemption to it (it neither
    #: spends the reorder window nor charges anyone's bypassed counter)
    resumed: bool = False
    #: the request's observability flight record
    #: (observability.tracing.RequestTrace, attached by the engine at
    #: submit when request tracing is on; None otherwise)
    trace: object = None
    #: admission priority (gateway-era field): 0 is baseline; a higher
    #: value widens the overtake budget against lower-priority queued
    #: requests by ``reorder_window * priority_gap`` (see module doc).
    #: Negative = the offline batch lane: interactive traffic passes
    #: it without bound and load shedding drops it first.
    priority: int = 0
    #: seconds after ``submit_time`` by which the request must have been
    #: admitted; the engine aborts still-QUEUED requests whose deadline
    #: expired (``finish_reason="abort"``, counted in
    #: ``serving.requests_aborted``).  None = no deadline.
    deadline_s: float | None = None
    #: the tenant this request bills against (gateway quota key); None
    #: for in-process callers
    tenant: str | None = None
    #: structured generation: the validated GrammarSpec constraining
    #: this request's output (None = free text).  The engine compiles
    #: and installs it at submit; the scheduler only carries it so
    #: admission and failover can see which requests are constrained.
    grammar: object = None

    @property
    def deadline_expired(self):
        """True when a deadline was set and has passed (measured from
        ``submit_time`` on the wall clock, like TTFT)."""
        return (self.deadline_s is not None
                and time.time() - self.submit_time > self.deadline_s)

    @property
    def prompt_len(self):
        return len(self.prompt_ids)

    @property
    def n_generated(self):
        return len(self.output_ids)

    @property
    def remaining_budget(self):
        """Decode steps left before length retirement.  The engine's
        adaptive horizon never exceeds the smallest remaining budget of
        any running request, so a horizon dispatch cannot overrun a
        lane's ``max_new_tokens`` limit."""
        return self.sampling.max_new_tokens - self.n_generated

    @property
    def queue_seconds(self):
        """Seconds spent waiting for a slot (None until admitted)."""
        if self.admit_time is None:
            return None
        return self.admit_time - self.submit_time

    @property
    def ttft(self):
        """Time-to-first-token in seconds, measured submit -> first
        sampled token, so it INCLUDES queue wait and prefill (None until
        the first token)."""
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    def record_token(self, token):
        """Append a sampled token; returns True when the request is done
        (EOS or max_new_tokens reached)."""
        if self.first_token_time is None:
            self.first_token_time = time.time()
        self.output_ids.append(int(token))
        eos = self.sampling.eos_token_id
        if eos is not None and int(token) == int(eos):
            self.finish_reason = FINISH_EOS
            return True
        if self.n_generated >= self.sampling.max_new_tokens:
            self.finish_reason = FINISH_LENGTH
            return True
        return False


class Scheduler:
    """FIFO admission over a fixed slot pool, with bounded-reorder
    co-bucketed batching via :meth:`pop_batch`."""

    def __init__(self, num_slots, reorder_window=8):
        self.num_slots = num_slots
        self.reorder_window = int(reorder_window)
        self.queue = deque()
        self.running = {}           # slot -> Request
        self._next_id = 0

    def submit(self, prompt_ids, sampling, priority=0, deadline_s=None,
               tenant=None, grammar=None):
        req = Request(self._next_id, list(prompt_ids),
                      sampling.validate(), priority=int(priority),
                      deadline_s=deadline_s, tenant=tenant,
                      grammar=grammar)
        self._next_id += 1
        self.queue.append(req)
        return req

    def overtake_cap(self, victim, overtaker, window=None):
        """The overtake budget of ``victim`` against ``overtaker``: how
        many times ``victim`` may be bypassed in total before requests
        like ``overtaker`` must stop passing it.  Equal (or lower)
        priority keeps the plain reorder window; each point of priority
        advantage adds one more window's worth of budget.  This single
        cap bounds BOTH reorder sources — same-bucket co-batching and
        the priority promotion pass — so the documented starvation
        bound (``window * (1 + max priority gap)`` total overtakes)
        holds across them combined.

        A batch-lane victim (``priority < 0``) has NO budget limit
        against interactive traffic: the cap is infinite, so the
        starvation bound applies only among interactive tiers (and
        among batch requests themselves, which keep the plain
        window)."""
        w = self.reorder_window if window is None else int(window)
        if victim.priority < 0 <= overtaker.priority:
            return float("inf")
        gap = max(0, int(overtaker.priority) - int(victim.priority))
        return w * (1 + gap)

    def promote(self, window=None):
        """Bounded stable priority promotion: bubble higher-priority
        queued requests toward the head, one overtake at a time, each
        hop allowed only while the passed request still has overtake
        budget (:meth:`overtake_cap`) — and charged against it.  Equal
        priorities never reorder (FIFO preserved), ``resumed`` requests
        are never passed (re-admission order after preemption is part
        of the bitwise-replay contract), and with ``window == 0`` the
        cap is 0 so this is a no-op (strict FIFO).  Idempotent: once
        the queue is priority-sorted within budget, no further hops
        happen and no further budget is charged."""
        q = list(self.queue)
        if len(q) < 2 or all(r.priority == q[0].priority for r in q):
            return
        out = []
        for r in q:
            pos = len(out)
            while pos > 0:
                s = out[pos - 1]
                if (s.resumed or s.priority >= r.priority
                        or s.bypassed >= self.overtake_cap(s, r, window)):
                    break
                pos -= 1
            for s in out[pos:]:
                s.bypassed += 1
            out.insert(pos, r)
        self.queue = deque(out)

    def admissible(self, free_slots):
        """Pop up to free_slots queued requests in strict FIFO order
        (join happens at the next decode-step boundary)."""
        out = []
        while self.queue and len(out) < free_slots:
            out.append(self.queue.popleft())
        return out

    def shed_victims(self, max_queue):
        """Load-shedding selection (the degradation ladder's level 3):
        the queued requests to drop so at most ``max_queue`` remain —
        lowest priority first, newest first within a priority, and
        never a ``resumed`` request (its tokens are already streamed to
        a client; shedding it would break the zero-dropped-tokens
        contract).  Pure selection: the victims are still queued when
        this returns — the caller aborts them, which removes them."""
        excess = len(self.queue) - max(0, int(max_queue))
        if excess <= 0:
            return []
        sheddable = [r for r in self.queue if not r.resumed]
        sheddable.sort(key=lambda r: (r.priority, -r.request_id))
        return sheddable[:excess]

    def pop_batch(self, free_slots, bucket_of=None, window=None):
        """Pop one co-bucketed admission batch of up to ``free_slots``
        requests.

        The queue head anchors the batch — it is ALWAYS admitted, so
        FIFO heads never starve.  The scan then extends the batch with
        later queued requests whose ``bucket_of(req)`` equals the
        anchor's, subject to the reorder window ``window`` (default: the
        scheduler's ``reorder_window``):

        * a contiguous same-bucket run behind the head batches freely
          (no reordering happens, so no window applies);
        * once any request has been skipped, admitting a request from
          behind it counts as an overtake; a request is never overtaken
          more than ``window`` times in total, and no admission reaches
          past the window once a skip exists;
        * a ``resumed`` request (preempted, waiting to be re-admitted)
          shares the head anchor's exemption: admitting it restores the
          order the preemption disturbed, so it neither consumes the
          window nor increments anyone's ``bypassed`` counter;
        * priorities widen the budget per overtaken request
          (:meth:`overtake_cap`): a :meth:`promote` pass runs first so
          higher-priority requests reach the head within budget, and a
          same-bucket join is allowed while every skipped request still
          has budget *against that candidate's priority*.

        With ``bucket_of=None`` or ``window<=0`` this degrades to strict
        FIFO (``admissible``), batching only the contiguous same-bucket
        prefix when ``bucket_of`` is given.
        """
        if free_slots <= 0 or not self.queue:
            return []
        self.promote(window)
        if bucket_of is None:
            return self.admissible(free_slots)
        w = self.reorder_window if window is None else int(window)
        q = list(self.queue)
        anchor_bucket = bucket_of(q[0])
        batch = [q[0]]
        skipped = []
        # once the reorder window is exhausted the batch is SEALED for
        # ordinary requests, but the scan keeps walking: resumes restore
        # order rather than reorder, so they may still join
        sealed = False
        for idx in range(1, len(q)):
            if len(batch) >= free_slots:
                break
            r = q[idx]
            if r.resumed and bucket_of(r) == anchor_bucket:
                batch.append(r)  # head-anchor exemption for resumes
                continue
            if sealed:
                continue
            if (any(s.priority >= 0 for s in skipped)
                    and idx >= max(w, 1)):
                sealed = True    # reordering beyond the window forbidden
                continue         # (batch-lane skips don't bound the scan)
            if bucket_of(r) == anchor_bucket:
                if any(s.bypassed >= self.overtake_cap(s, r, w)
                       for s in skipped):
                    sealed = True  # someone ahead is at their overtake cap
                    continue
                batch.append(r)
                for s in skipped:
                    s.bypassed += 1
            else:
                skipped.append(r)
                if w <= 0 or (r.priority >= 0 and r.bypassed >= w):
                    sealed = True  # nobody may pass this request anymore
        taken = {id(r) for r in batch}
        self.queue = deque(r for r in q if id(r) not in taken)
        return batch

    def start(self, req, slot):
        req.status = RUNNING
        req.slot = slot
        req.resumed = False
        req.admit_time = time.time()
        self.running[slot] = req

    def finish(self, req):
        req.status = FINISHED
        del self.running[req.slot]

    def requeue_front(self, req):
        """Preempt a RUNNING request back to the queue head: it gives up
        its slot (and, in the paged engine, its KV blocks) but keeps its
        generated tokens, and is first in line to be re-admitted.  The
        engine re-prefills prompt + generated-so-far on re-admission, so
        preemption is invisible in the output stream."""
        del self.running[req.slot]
        req.status = WAITING
        req.slot = None
        req.resumed = True
        self.queue.appendleft(req)

    @property
    def queue_depth(self):
        return len(self.queue)

    @property
    def has_work(self):
        return bool(self.queue or self.running)
