"""Ragged paged-attention over the unified KV block pool.

Decode attention against a slotted cache reads the full ``max_seq``
row of every lane under a position mask — short sequences pay bandwidth
for the whole row (DECODE_BENCH.json: fused decode stuck at 41-47% of
the weight roofline at b1 and 25.5% at b8, where the masked reads are 8
full rows per step).  Paged attention instead walks each lane's block
table and reads ONLY the table-mapped blocks, so per-step KV traffic is
proportional to the live sequence length.

Two implementations behind one entry point:

* :func:`paged_attention` — the router.  A Pallas TPU kernel serves
  every TPU query window — single-token decode, speculative K+1 verify
  windows, and chunked-prefill windows all hit the kernel; CPU tier-1
  runs the XLA fallback (the parity reference).  The kernel executes
  the fallback's exact per-block recurrence; since the two compile as
  separate programs, raw outputs agree to reassociation-level ulps
  (exact at most shapes), and the serving gate is BITWISE stream
  equality of whole-engine runs under kernel routing, which CPU tests
  assert in interpret mode.  Override with
  ``PADDLE_TPU_PAGED_ATTN=xla|pallas``.
* **XLA fallback** — a blockwise online-softmax ``lax.scan`` over the
  table entries (flash-attention recurrence: running max ``m``, running
  normalizer ``l``, unnormalized accumulator ``acc``).  The scan is the
  engine's PARITY REFERENCE: a block with no visible keys contributes
  exactly nothing — its masked scores sit at the finite ``NEG_INF``
  floor so ``m`` is unchanged (``max(m, NEG_INF) == m``), its
  probabilities are forced to literal 0.0, and ``l``/``acc`` pass
  through bitwise (``x * 1.0 + 0.0 == x``).  Outputs are therefore
  invariant to the STATIC number of table columns ``nb``, which is what
  keeps batched/horizoned paged decode bitwise-equal to sequential
  generation even though the engine re-buckets ``nb`` as sequences grow.
* **Pallas TPU kernel** — grid ``(batch, nb)`` with the flattened block
  table and per-lane lengths as scalar prefetch (the table drives the
  k/v BlockSpec index maps, so each grid cell DMAs exactly one pool
  block); ``pl.when`` skips cells whose block starts past the lane's
  visible window, so a short sequence's tail blocks cost neither
  bandwidth nor compute.  The query window is a static dimension s >= 1:
  each grid cell scores all s query rows against its block under an
  in-kernel causal mask (``key_idx <= pos[b] + row``), so spec verify
  windows and chunked-prefill chunks run the same kernel as s == 1
  decode.  f32 accumulation in VMEM scratch, finalized on the last
  block column.

Layout contract (matches ``kv_cache.PagedKV``): q ``[B, s, QH, D]``,
pools ``[NB, bs, KH, D]`` with GQA group size ``G = QH // KH`` (query
head ``h`` reads kv head ``h // G``), tables ``[B, nb]`` int32 (0 =
scratch), pos ``[B]`` int32.  Returns ``[B, s, QH, D]`` in q's dtype.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30    # finite floor: keeps exp(s - m) NaN-free when a
#                    query row has no visible key in a block

try:  # pallas import is TPU-oriented; CPU-only builds may lack it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
    # jax renamed TPUCompilerParams -> CompilerParams across releases;
    # accept either so interpret-mode CPU tests run on both
    _COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams", None)
except Exception:  # pragma: no cover - exercised only without pallas
    pl = pltpu = None
    _HAVE_PALLAS = False
    _COMPILER_PARAMS = None


def paged_attention(q, k_pool, v_pool, tables, pos,
                    k_scale=None, v_scale=None):
    """Route to the Pallas ragged kernel (TPU, any window s >= 1) or
    the XLA online-softmax fallback (CPU tier-1, which is also the
    parity reference for every s).

    ``k_scale``/``v_scale`` ([NB, bs] f32, or None) mark a quantized
    pool: both implementations dequantize each gathered block token-wise
    (``block.astype(f32) * scale``) before the softmax math, so the
    int8 path reuses the exact fp recurrence — and inherits its
    nb-invariance — just over dequantized values."""
    impl = os.environ.get("PADDLE_TPU_PAGED_ATTN", "auto")
    use_pallas = impl == "pallas" or (
        impl == "auto" and jax.default_backend() == "tpu")
    if use_pallas:
        # forcing `pallas` off-TPU runs the kernel in interpret mode —
        # how CPU tests drive the kernel through whole-engine (and
        # shard_map per-shard) paths and assert bitwise parity with the
        # fallback
        return _pallas_paged_attention(
            q, k_pool, v_pool, tables, pos, k_scale, v_scale,
            interpret=jax.default_backend() != "tpu")
    return _xla_paged_attention(q, k_pool, v_pool, tables, pos,
                                k_scale, v_scale)


# ------------------------------------------------------------------ XLA

def _xla_paged_attention(q, k_pool, v_pool, tables, pos,
                         k_scale=None, v_scale=None):
    """Blockwise online-softmax over the block table, one ``lax.scan``
    step per table column.  Fixed shapes per step ([B, bs] gathers), so
    the whole thing traces into the engine's horizon scan; see the
    module docstring for the nb-invariance argument (dequantizing a
    gathered block is an elementwise pre-multiply on values the masked
    positions never contribute, so the argument survives int8 pools
    unchanged)."""
    b, s, qh, d = q.shape
    bs, kh = k_pool.shape[1], k_pool.shape[2]
    g = qh // kh
    nb = tables.shape[1]
    scale = 1.0 / math.sqrt(d)

    qg = (q.astype(jnp.float32) * scale).reshape(b, s, kh, g, d)
    q_pos = pos[:, None] + jnp.arange(s, dtype=pos.dtype)        # [B, s]

    def block_step(carry, i):
        m, l, acc = carry
        blocks = jnp.take(tables, i, axis=1)                     # [B]
        kb = k_pool[blocks].astype(jnp.float32)                  # [B,bs,KH,D]
        vb = v_pool[blocks].astype(jnp.float32)
        if k_scale is not None:
            kb = kb * k_scale[blocks][:, :, None, None]
            vb = vb * v_scale[blocks][:, :, None, None]
        sc = jnp.einsum("bskgd,btkd->bskgt", qg, kb)
        key_idx = i * bs + jnp.arange(bs, dtype=pos.dtype)       # [bs]
        vis = key_idx[None, None, :] <= q_pos[:, :, None]        # [B,s,bs]
        vis = vis[:, :, None, None, :]                           # [B,s,1,1,bs]
        sc = jnp.where(vis, sc, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
        # exact-zero masked probabilities (not exp(NEG_INF - m)): padded
        # blocks and padded key columns contribute literal +0.0, which
        # is what makes the output bitwise-invariant to nb
        p = jnp.where(vis, jnp.exp(sc - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + \
            jnp.einsum("bskgt,btkd->bskgd", p, vb)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, s, kh, g), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, s, kh, g), jnp.float32)
    acc0 = jnp.zeros((b, s, kh, g, d), jnp.float32)
    (_, l, acc), _ = jax.lax.scan(block_step, (m0, l0, acc0),
                                  jnp.arange(nb))
    # every query row sees at least key 0 (key_idx 0 <= q_pos), so l > 0
    out = acc / l[..., None]
    return out.reshape(b, s, qh, d).astype(q.dtype)


# --------------------------------------------------------------- Pallas

def _paged_attn_kernel(tables, pos, q_ref, k_ref, v_ref, *refs,
                       block_size, groups, nb, q_len, scale, quantized):
    """One grid cell = (lane b, table column i): accumulate pool block
    ``tables[b, i]`` into lane b's online-softmax state for all q_len
    query rows at once.  The k/v BlockSpec index maps already selected
    the pool block from the scalar-prefetched table, so refs hold
    exactly one block.  Query row r (a static offset into the window)
    sits at absolute position ``pos[b] + r``, and the causal mask
    ``key_idx <= pos[b] + r`` is evaluated in-kernel per row — the same
    visibility rule, masking (exact-zero probabilities), and update
    order the XLA fallback applies, so the recurrences are term-for-
    term identical.  On a quantized pool two extra [1, bs]
    scale refs ride between the pool refs and the output: the block is
    dequantized token-wise right after its DMA, before any softmax
    math."""
    if quantized:
        ksc_ref, vsc_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        ksc_ref = vsc_ref = None
        o_ref, m_ref, l_ref, acc_ref = refs
    b, i = pl.program_id(0), pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    p_b = pos[b]

    # skip blocks that start past the window's deepest visible key
    # (row q_len-1 sees up to pos + q_len - 1): a retired/short lane's
    # tail blocks are never read at all
    @pl.when(i * block_size <= p_b + (q_len - 1))
    def _accumulate():
        kh = k_ref.shape[2]
        d = k_ref.shape[3]
        q = q_ref[0].astype(jnp.float32) * scale          # [s, QH, D]
        q = q.reshape(q_len, kh, groups, d)
        k = k_ref[0].astype(jnp.float32)                  # [bs, KH, D]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ksc_ref[0][:, None, None]
            v = v * vsc_ref[0][:, None, None]
        sc = jax.lax.dot_general(
            q, k, (((3,), (2,)), ((1,), (1,))),
            preferred_element_type=jnp.float32)           # [KH, s, G, bs]
        row = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        key_idx = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 3)
        vis = key_idx <= p_b + row
        sc = jnp.where(vis, sc, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
        p = jnp.where(vis, jnp.exp(sc - m_new[..., None]), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p, v, (((3,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)           # [KH, s, G, D]
        acc_ref[...] = acc_ref[...] * alpha[..., None] + pv
        m_ref[...] = m_new

    @pl.when(i == nb - 1)
    def _finalize():
        out = acc_ref[...] / l_ref[...][..., None]        # [KH, s, G, D]
        out = out.transpose(1, 0, 2, 3)                   # [s, KH, G, D]
        o_ref[0] = out.reshape(o_ref.shape[1:]).astype(o_ref.dtype)


def _pallas_paged_attention(q, k_pool, v_pool, tables, pos,
                            k_scale=None, v_scale=None, *,
                            interpret=False):
    """Ragged kernel for any static query window s >= 1: grid (B, nb),
    block table + lane lengths scalar-prefetched so the k/v index maps
    gather pool blocks directly and ``pl.when`` culls dead columns.
    The accumulator carries all s rows ([KH, s, G] / [KH, s, G, D]
    VMEM scratch), so one pool-block DMA serves the whole window —
    decode (s=1), spec verify (s=K+1), and chunked-prefill windows
    share the program structure.  Quantized pools add two [1, bs]
    scale inputs gathered through the same table index map as their
    blocks.  ``interpret=True`` runs the kernel in Pallas interpret
    mode (the CPU test path)."""
    if not _HAVE_PALLAS:  # pragma: no cover
        return _xla_paged_attention(q, k_pool, v_pool, tables, pos,
                                    k_scale, v_scale)
    b, s, qh, d = q.shape
    bs, kh = k_pool.shape[1], k_pool.shape[2]
    g = qh // kh
    nb = tables.shape[1]
    quantized = k_scale is not None

    kernel = functools.partial(
        _paged_attn_kernel, block_size=bs, groups=g, nb=nb, q_len=s,
        scale=1.0 / math.sqrt(d), quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, s, qh, d),
                     lambda bb, i, tables, pos: (bb, 0, 0, 0)),
        pl.BlockSpec((1, bs, kh, d),
                     lambda bb, i, tables, pos: (tables[bb, i], 0, 0, 0)),
        pl.BlockSpec((1, bs, kh, d),
                     lambda bb, i, tables, pos: (tables[bb, i], 0, 0, 0)),
    ]
    operands = [tables, pos, q, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bs),
                         lambda bb, i, tables, pos: (tables[bb, i], 0)),
            pl.BlockSpec((1, bs),
                         lambda bb, i, tables, pos: (tables[bb, i], 0)),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # tables, pos
        grid=(b, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, s, qh, d),
                               lambda bb, i, tables, pos: (bb, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kh, s, g), jnp.float32),       # running max m
            pltpu.VMEM((kh, s, g), jnp.float32),       # running sum l
            pltpu.VMEM((kh, s, g, d), jnp.float32),    # accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s, qh, d), q.dtype),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(*operands)


# backwards-compat alias (pre-s>1 name)
_pallas_paged_decode = _pallas_paged_attention
