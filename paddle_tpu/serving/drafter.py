"""Self-drafting token proposal for speculative decoding.

Prompt-lookup / n-gram drafting: a lane's best guess for its next K
tokens is whatever followed the LAST earlier occurrence of its current
``ngram``-token suffix in its own prompt+output history.  No second
model, no host round-trip — the history already lives on device (the
engine carries a ``[num_slots, max_seq_len]`` token buffer through the
decode scan), and the matcher is a pure gather/compare, so it traces
straight into the compiled decode program.

The drafter is allowed to be wrong: rejected draft positions cost one
wasted lane-column of the verify forward and nothing else (the engine's
acceptance rule only ever emits tokens the model itself would have
produced, and rejected-position KV writes are overwritten before they
can be read — see engine.py).  It is therefore deliberately simple and
cheap; the only contract is the **sentinel**: a position with no valid
proposal must return ``-1``, which can never equal a sampled token id,
so invalid drafts are never accepted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def draft_tokens(hist, lengths, k, ngram=2):
    """Propose up to ``k`` draft tokens per lane by suffix matching.

    hist [N, S] int32   per-lane token history; positions ``< lengths``
                        are valid (prompt followed by emitted tokens)
    lengths [N] int32   valid history length per lane (``pos + 1`` in
                        engine terms: prompt plus tokens sampled so far)
    k                   static draft width (>= 1)
    ngram               static suffix length to match (>= 1)

    Returns [N, k] int32 draft ids, ``-1`` where no proposal exists
    (history shorter than ``ngram + 1``, no earlier occurrence of the
    suffix, or the continuation would run past the valid history).

    Matching prefers the occurrence with the most RUNWAY — known history
    after the match to draft from, capped at ``k`` — and breaks runway
    ties by recency.  (Pure recency would pick the match closest to the
    end of history, which for a cyclic stream is the one with nothing
    after it to copy: drafts would cap at 1 useful token however large
    ``k`` is.)  Everything is fixed-shape: the window compare is an
    [S-ngram+1, ngram] gather and the winner an argmax, so the whole
    proposal compiles into the decode scan body.
    """
    if k < 1:
        raise ValueError(f"draft width k must be >= 1, got {k}")
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {ngram}")
    n, s = hist.shape
    if s < ngram + 1:
        return jnp.full((n, k), -1, jnp.int32)

    starts = jnp.arange(s - ngram + 1, dtype=jnp.int32)
    offs = jnp.arange(ngram, dtype=jnp.int32)
    ks = jnp.arange(k, dtype=jnp.int32)

    def one(row, length):
        # the lane's current trailing ngram (clamped start keeps the
        # slice in bounds; short histories are rejected by `enough`)
        g0 = jnp.maximum(length - ngram, 0)
        g = jax.lax.dynamic_slice(row, (g0,), (ngram,))
        # every candidate window hist[j : j+ngram], compared at once
        win = row[starts[:, None] + offs[None, :]]        # [S-n+1, ngram]
        hit = jnp.all(win == g[None, :], axis=1)
        # a usable match must END strictly before the last valid token
        # so at least one continuation token is known history (this
        # also excludes the trailing window matching itself)
        hit &= (starts + ngram) <= (length - 1)
        enough = length >= (ngram + 1)
        # rank matches by runway (continuation tokens inside known
        # history, capped at k), then by recency; encode as
        # runway * (S+1) + start so one argmax resolves both
        runway = jnp.clip(length - (starts + ngram), 0, k)
        score = jnp.where(hit & enough, runway * (s + 1) + starts, -1)
        top = jnp.max(score)
        best = jnp.where(top >= 0, top % (s + 1), -1)
        cont = best + ngram + ks                          # continuation idx
        cand = row[jnp.clip(cont, 0, s - 1)]
        valid = (best >= 0) & (cont < length)
        return jnp.where(valid, cand, -1).astype(jnp.int32)

    return jax.vmap(one)(hist, lengths)


def forced_chain(states, next_table, forced, k):
    """Constraint-aware draft proposals: chain the grammar's FORCED
    tokens from each lane's DFA state.

    When a lane's DFA state admits exactly one legal token — closing
    braces, quoted keys, commas, the skeleton of any JSON output —
    ``forced[state]`` names it and the model's verify forward must
    agree (every other logit is at the mask floor), so proposing it is
    a ~100%-acceptance draft.  Chains extend while each successor state
    stays forced, up to ``k``; the first non-forced state ends the
    chain with ``-1`` sentinels from there on, and the engine overlays
    these proposals on the n-gram drafter's (forced wins where
    present).  Unconstrained lanes sit in the accept-all sentinel state
    whose ``forced`` entry is ``-1``, so they never chain.

    states [N] int32       per-lane DFA state ids (slab-global rows)
    next_table [S, V] i32  dense transition table (slab rows)
    forced [S] int32       the state's sole legal token, or -1
    k                      static chain length (the draft width)

    Returns [N, k] int32 proposals with ``-1`` where not forced.
    """
    cols = []
    st = states
    ok = None
    for _ in range(k):
        f = forced[st]
        ok = (f >= 0) if ok is None else (ok & (f >= 0))
        cols.append(jnp.where(ok, f, -1))
        st = jnp.where(ok, next_table[st, jnp.maximum(f, 0)], st)
    return jnp.stack(cols, axis=1)
