"""Gateway router layer: N in-process engine replicas behind one front
door, with prefix-affinity session routing.

Two pieces:

* :class:`EngineWorker` — the ownership boundary between the threaded
  HTTP layer and a (single-threaded) :class:`~..engine.Engine`.  One
  daemon thread per replica owns every ``submit()/step()/abort()`` on
  its engine; other threads talk to it through a command inbox and get
  a :class:`StreamHandle` back.  After every ``step()`` the worker
  flushes each tracked request's newly harvested tokens into its
  handle's queue — that per-horizon flush is exactly the granularity
  SSE chunks stream at, and since the engine's sampling is a pure
  function of ``(seed, token index, logits)``, the streamed token
  sequence is bitwise what in-process ``Engine.run()`` produces.
* :class:`PrefixAffinityRouter` — picks a replica per request.  The
  affinity key is the prompt's leading **prefix-cache blocks**, chunked
  exactly the way the radix cache keys its trie
  (``tuple(tokens[:k * block_size])`` — see ``PrefixCache._walk``), so
  two prompts sharing a system prompt share a key and land on the same
  replica, where the radix store already holds those blocks.  Keys map
  to replicas by rendezvous (highest-random-weight) hashing — stable
  under replica add/remove — over the **healthy** replica set only:
  per-replica health is the engine's SLO signal (the same one
  ``/readyz`` serves), so a replica burning its error budget stops
  receiving new sessions until it recovers.  Prompts shorter than one
  block have no affinity key and fall back to the least-loaded healthy
  replica (queue depth + active slots from the engine's scheduler).

Graceful replica removal composes the two: ``router.remove(worker)``
stops routing to it, the worker finishes its in-flight work, and
``Engine.drain()`` releases every pool block (asserting the block-leak
invariant) before the engine is closed.
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time

from ..scheduler import FINISHED


class StreamHandle:
    """The caller-side view of one request running on a worker thread.

    ``events`` is a queue of ``("tokens", [ids])`` chunks — one per
    decode horizon the request rode — terminated by exactly one
    ``("finish", finish_reason)``.  ``request`` is the live engine
    Request (its ``output_ids``/``finish_reason`` fill in as the worker
    steps); treat it as read-only from other threads."""

    def __init__(self, request, worker):
        self.request = request
        self.worker = worker
        self.events = queue.Queue()
        #: tokens already flushed into ``events``
        self.sent = 0

    @property
    def request_id(self):
        return self.request.request_id


class EngineWorker:
    """Drives one Engine on a dedicated daemon thread.

    All engine mutation happens on that thread: ``submit()``/
    ``abort()``/``drain()`` enqueue commands and block on a reply, the
    loop applies them between horizon dispatches, steps while work
    exists, and flushes per-request token deltas after every step.
    Reads exposed to other threads (``load``, ``healthy``, ``stats()``)
    are GIL-atomic snapshots of host-side counters.

    The worker is engine-shape agnostic: any object with the Engine
    duck type below drives the same loop — the single-chip ``Engine``
    and the tensor-parallel ``sharded.MeshEngine`` both qualify, so a
    router can mix single-chip and mesh replicas behind one front
    door."""

    #: the Engine duck type the worker loop actually exercises
    _ENGINE_API = ("submit", "abort", "step", "drain", "stats", "close")

    def __init__(self, engine, name=None):
        missing = [a for a in self._ENGINE_API
                   if not callable(getattr(engine, a, None))]
        if not hasattr(engine, "scheduler"):
            missing.append("scheduler")
        if missing:
            raise TypeError(
                f"EngineWorker needs an Engine-shaped object; "
                f"{type(engine).__name__} lacks {missing}")
        self.engine = engine
        self.name = name or engine._profiler_name
        self._inbox = queue.Queue()
        self._pending = {}           # request_id -> StreamHandle
        self._draining = False
        self._drained = threading.Event()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name=f"gateway.worker:{self.name}",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- control
    def submit(self, prompt_ids, sampling=None, priority=0,
               deadline_s=None, tenant=None, trace_args=None,
               timeout=30.0):
        """Submit on the worker thread; returns a :class:`StreamHandle`.
        ``trace_args`` (tenant/priority/hop_s from the gateway) are
        appended to the flight record as the ``gateway`` event — on the
        engine thread, so event order stays queued -> gateway ->
        prefill.  Raises whatever ``Engine.submit`` raises (validation)
        or RuntimeError when the replica is draining/stopped."""
        if not self.alive:
            raise RuntimeError(f"replica {self.name} is stopped")
        reply = queue.Queue(1)
        self._inbox.put(("submit", dict(
            prompt_ids=prompt_ids, sampling=sampling, priority=priority,
            deadline_s=deadline_s, tenant=tenant), trace_args, reply))
        kind, value = reply.get(timeout=timeout)
        if kind == "error":
            raise value
        return value

    def abort(self, handle, cause="client_disconnect"):
        """Abort a tracked request (fire-and-forget; the handle's queue
        still receives its terminal ``("finish", "abort")``)."""
        self._inbox.put(("abort", handle, cause, None))

    def drain(self, timeout=120.0):
        """Stop accepting submissions, let in-flight AND queued requests
        run to completion, then ``Engine.drain()`` (releases every pool
        block, asserts the block-leak invariant).  Blocks until done.
        Idempotent; the worker stays alive (for ``stats()``) until
        ``stop()``."""
        self._inbox.put(("drain", None, None, None))
        if not self._drained.wait(timeout):
            raise TimeoutError(f"worker {self.name} drain timed out")

    def stop(self, timeout=30.0):
        """Stop the driving thread (does NOT close the engine — the
        owner does, after ``drain()``)."""
        if self._stopped:
            return
        self._inbox.put(("stop", None, None, None))
        self._thread.join(timeout)
        self._stopped = True

    # -------------------------------------------------------------- health
    @property
    def alive(self):
        return self._thread.is_alive() and not self._stopped

    @property
    def draining(self):
        return self._draining

    @property
    def healthy(self):
        """Routable: thread alive, not draining, and the engine's SLO
        tracker (if any) reports healthy — the same signal the
        telemetry server's ``/readyz`` flips on."""
        if not self.alive or self._draining:
            return False
        slo = self.engine.slo
        return slo is None or slo.healthy

    @property
    def load(self):
        """Instantaneous load for least-loaded routing: queued +
        running requests."""
        return (self.engine.scheduler.queue_depth
                + len(self.engine.scheduler.running))

    @property
    def prefix_block_size(self):
        return self.engine._block_size

    def stats(self):
        """The engine's ``stats()`` snapshot plus worker state.  Host
        counters only — safe to call from any thread."""
        s = self.engine.stats()
        s["worker"] = {"name": self.name, "alive": self.alive,
                       "draining": self._draining,
                       "healthy": self.healthy, "load": self.load,
                       "streams": len(self._pending)}
        return s

    # ---------------------------------------------------------- the thread
    def _loop(self):
        while True:
            busy = self.engine.scheduler.has_work
            try:
                cmd = (self._inbox.get_nowait() if busy
                       else self._inbox.get(timeout=0.05))
            except queue.Empty:
                cmd = None
            if cmd is not None and self._apply(cmd):
                return
            # apply everything already queued before paying for a step
            while True:
                try:
                    cmd = self._inbox.get_nowait()
                except queue.Empty:
                    break
                if self._apply(cmd):
                    return
            if self.engine.scheduler.has_work:
                self.engine.step()
                if self._flush():
                    # yield the GIL before the next dispatch so handler
                    # threads woken by the flush get to write their SSE
                    # frames now, not a switch-interval (~5 ms) later
                    time.sleep(0)
            elif self._draining and not self._drained.is_set():
                self.engine.drain()      # queue empty: releases blocks
                self._drained.set()

    def _apply(self, cmd):
        """Execute one command on the engine thread; True = stop."""
        op, arg, extra, reply = cmd
        if op == "stop":
            return True
        if op == "submit":
            if self._draining:
                reply.put(("error", RuntimeError(
                    f"replica {self.name} is draining")))
                return False
            try:
                req = self.engine.submit(**arg)
            except Exception as e:
                reply.put(("error", e))
                return False
            if extra and req.trace is not None:
                from ...observability import tracing as _obs_tracing

                req.trace.add(_obs_tracing.GATEWAY, **extra)
            handle = StreamHandle(req, self)
            self._pending[req.request_id] = handle
            reply.put(("ok", handle))
        elif op == "abort":
            handle, cause = arg, extra
            if handle.request.status != FINISHED:
                self.engine.abort(handle.request, cause=cause)
                self._flush()
        elif op == "drain":
            self._draining = True
        return False

    def _flush(self):
        """Push each tracked request's newly harvested tokens (and its
        terminal event) into its handle queue — the per-horizon flush
        the SSE stream rides.  Returns True if any event was pushed."""
        done, pushed = [], False
        for rid, h in self._pending.items():
            n = h.request.n_generated
            if n > h.sent:
                h.events.put(("tokens",
                              list(h.request.output_ids[h.sent:n])))
                h.sent = n
                pushed = True
            if h.request.status == FINISHED:
                h.events.put(("finish", h.request.finish_reason))
                done.append(rid)
                pushed = True
        for rid in done:
            del self._pending[rid]
        return pushed


def _rendezvous_weight(key, name):
    """Deterministic highest-random-weight score for (affinity key,
    replica name) — stable across processes (no PYTHONHASHSEED
    dependence), uniform enough that distinct system prompts spread
    over replicas."""
    h = hashlib.blake2b(repr(key).encode() + b"|" + name.encode(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


class PrefixAffinityRouter:
    """Routes requests over a set of :class:`EngineWorker` replicas.

    ``affinity_blocks`` bounds how many leading prefix-cache blocks key
    the session: hashing MORE blocks than the shared system prompt
    would scatter same-prefix sessions (their suffixes differ), hashing
    fewer costs nothing — so the default is small."""

    def __init__(self, workers, affinity_blocks=2):
        if not workers:
            raise ValueError("router needs at least one worker")
        self.workers = list(workers)
        self.affinity_blocks = int(affinity_blocks)

    def affinity_key(self, prompt_ids):
        """The routing key: the prompt's leading full blocks, chunked
        with the radix cache's block size (``None`` when the prompt is
        shorter than one block — no reusable prefix to be affine to)."""
        bs = self.workers[0].prefix_block_size
        nb = min(self.affinity_blocks, len(prompt_ids) // bs)
        if nb <= 0:
            return None
        return tuple(int(t) for t in prompt_ids[:nb * bs])

    def route(self, prompt_ids):
        """Pick a replica: ``(worker, how)`` where ``how`` is
        ``"affine"`` (rendezvous hash of the prefix key over healthy
        replicas) or ``"least-loaded"`` (no key).  ``(None, "shed")``
        when no replica is healthy — the gateway's 503 signal."""
        live = [w for w in self.workers if w.healthy]
        if not live:
            return None, "shed"
        key = self.affinity_key(prompt_ids)
        if key is None:
            return min(live, key=lambda w: (w.load, w.name)), \
                "least-loaded"
        return max(live,
                   key=lambda w: _rendezvous_weight(key, w.name)), \
            "affine"

    def submit(self, prompt_ids, sampling=None, **kw):
        """Route + submit in one call (convenience for tests/benches);
        returns ``(handle, worker, how)`` or raises RuntimeError when
        every replica is shedding."""
        worker, how = self.route(prompt_ids)
        if worker is None:
            raise RuntimeError("no healthy replica")
        return worker.submit(prompt_ids, sampling=sampling, **kw), \
            worker, how

    def remove(self, worker, close_engine=True):
        """Graceful replica removal: stop routing to it, drain it
        (in-flight work finishes, every pool block released), stop its
        thread, and optionally close its engine."""
        self.workers.remove(worker)
        worker.drain()
        worker.stop()
        if close_engine:
            worker.engine.close()
