"""Gateway router layer: N in-process engine replicas behind one front
door, with prefix-affinity session routing and mid-stream failover.

Three pieces:

* :class:`EngineWorker` — the ownership boundary between the threaded
  HTTP layer and a (single-threaded) :class:`~..engine.Engine`.  One
  daemon thread per replica owns every ``submit()/step()/abort()`` on
  its engine; other threads talk to it through a command inbox and get
  a :class:`StreamHandle` back.  After every ``step()`` the worker
  flushes each tracked request's newly harvested tokens into its
  handle's queue — that per-horizon flush is exactly the granularity
  SSE chunks stream at, and since the engine's sampling is a pure
  function of ``(seed, token index, logits)``, the streamed token
  sequence is bitwise what in-process ``Engine.run()`` produces.
* :class:`PrefixAffinityRouter` — picks a replica per request.  The
  affinity key is the prompt's leading **prefix-cache blocks**, chunked
  exactly the way the radix cache keys its trie
  (``tuple(tokens[:k * block_size])`` — see ``PrefixCache._walk``), so
  two prompts sharing a system prompt share a key and land on the same
  replica, where the radix store already holds those blocks.  Keys map
  to replicas by rendezvous (highest-random-weight) hashing — stable
  under replica add/remove — over the **healthy** replica set only:
  per-replica health is the engine's SLO signal (the same one
  ``/readyz`` serves), so a replica burning its error budget stops
  receiving new sessions until it recovers.  Prompts shorter than one
  block have no affinity key and fall back to the least-loaded healthy
  replica (queue depth + active slots from the engine's scheduler).

* :class:`FleetSupervisor` — the watchdog + failover loop.  Each
  worker's heartbeat ticks once per loop iteration; a worker whose
  thread has died, or that holds work but hasn't heartbeat within
  ``watchdog_timeout_s`` (a hung dispatch — e.g. a wedged collective),
  is **condemned**: its in-flight requests are aborted on the dead
  engine (accounting closure), its ``serving.*`` provider is
  unregistered via ``Engine.close()``, and every stream it held is
  re-dispatched to a surviving replica carrying ``prompt + tokens
  already flushed``.  The adopting engine re-prefills that history
  through the PR 6 resume path — whose consistency check *asserts* the
  re-sampled boundary token equals the last one the client saw — so
  because sampling is a pure function of ``fold_in(seed, n_generated)``,
  the failed-over stream is byte-identical to an uninterrupted run
  with zero duplicated and zero dropped tokens.

Graceful replica removal composes the pieces: ``router.remove(worker)``
stops routing to it, the worker finishes its in-flight work, and
``Engine.drain()`` releases every pool block (asserting the block-leak
invariant) before the engine is closed.
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time

from ...observability import events as _obs_events
from ..faults import (FAULT_STALL, SITE_WORKER_DISPATCH,
                      SITE_WORKER_SUBMIT, _SRV_FAILOVERS, _SRV_RETRIES,
                      DispatchFault, RetryPolicy, TransientSubmitError,
                      WorkerCrash, WorkerDeadError)
from ..scheduler import (FINISH_ABORT, FINISH_EOS, FINISH_LENGTH,
                         FINISHED)


class StreamHandle:
    """The caller-side view of one request running on a worker thread.

    ``events`` is a queue of ``("tokens", [ids])`` chunks — one per
    decode horizon the request rode — terminated by exactly one
    ``("finish", finish_reason)``.  ``request`` is the live engine
    Request (its ``output_ids``/``finish_reason`` fill in as the worker
    steps); treat it as read-only from other threads.

    Under failover the handle is the stable identity the client keeps
    while ``request``/``worker`` are rebound to the adopting replica —
    ``lock`` guards that swap, and ``abort()`` routes a cancellation to
    whichever replica currently holds the request (or, mid-swap, flags
    ``abort_requested`` so the supervisor cancels the pending
    re-dispatch instead)."""

    def __init__(self, request, worker):
        self.request = request
        self.worker = worker
        self.events = queue.Queue()
        #: tokens already flushed into ``events``
        self.sent = 0
        #: guards request/worker rebinding during failover
        self.lock = threading.Lock()
        #: True between condemnation and adoption by a new replica
        self.failing_over = False
        #: client abort seen while failing over (cancels the re-dispatch)
        self.abort_requested = False
        #: completed replica swaps this stream survived
        self.failovers = 0

    @property
    def request_id(self):
        return self.request.request_id

    def abort(self, cause="client_disconnect"):
        """Abort this stream wherever it currently lives.  Safe during
        failover: if the request is between replicas the pending
        re-dispatch is cancelled; otherwise the abort lands on the
        worker that holds the request *now* (fire-and-forget — the
        handle still receives its terminal ``("finish", "abort")``)."""
        with self.lock:
            if self.failing_over:
                self.abort_requested = True
                return
            worker = self.worker
        worker._inbox.put(("abort", self, cause, None))


class EngineWorker:
    """Drives one Engine on a dedicated daemon thread.

    All engine mutation happens on that thread: ``submit()``/
    ``abort()``/``drain()`` enqueue commands and block on a reply, the
    loop applies them between horizon dispatches, steps while work
    exists, and flushes per-request token deltas after every step.
    Reads exposed to other threads (``load``, ``healthy``, ``stats()``)
    are GIL-atomic snapshots of host-side counters.

    The worker is engine-shape agnostic: any object with the Engine
    duck type below drives the same loop — the single-chip ``Engine``
    and the tensor-parallel ``sharded.MeshEngine`` both qualify, so a
    router can mix single-chip and mesh replicas behind one front
    door."""

    #: the Engine duck type the worker loop actually exercises
    _ENGINE_API = ("submit", "abort", "step", "drain", "stats", "close")

    def __init__(self, engine, name=None, faults=None,
                 watchdog_timeout_s=None):
        missing = [a for a in self._ENGINE_API
                   if not callable(getattr(engine, a, None))]
        if not hasattr(engine, "scheduler"):
            missing.append("scheduler")
        if missing:
            raise TypeError(
                f"EngineWorker needs an Engine-shaped object; "
                f"{type(engine).__name__} lacks {missing}")
        self.engine = engine
        self.name = name or engine._profiler_name
        self._inbox = queue.Queue()
        self._pending = {}           # request_id -> StreamHandle
        self._draining = False
        self._drained = threading.Event()
        self._stopped = False
        #: fault-injection hook (FaultInjector or None); shared per-fleet
        self._faults = faults
        #: heartbeat staleness past this (while holding work) = stalled;
        #: None disables the local check (the supervisor may set its own)
        self.watchdog_timeout_s = watchdog_timeout_s
        self._heartbeat = time.monotonic()
        #: set by the supervisor: no longer part of the fleet
        self._condemned = False
        #: the engine thread died on an exception (vs clean stop)
        self._crashed = False
        self._crash_error = None
        self._dispatch_faults = 0    # transient dispatch errors retried
        self._unstall = threading.Event()  # test valve: release a stall
        self._thread = threading.Thread(
            target=self._loop, name=f"gateway.worker:{self.name}",
            daemon=True)
        self._thread.start()

    def set_faults(self, injector):
        """Arm (or disarm, with None) fault injection on this worker
        AND its engine's admission site."""
        self._faults = injector
        if hasattr(self.engine, "install_faults"):
            self.engine.install_faults(injector, scope=self.name)

    # ------------------------------------------------------------- control
    def submit(self, prompt_ids, sampling=None, priority=0,
               deadline_s=None, tenant=None, grammar=None,
               trace_args=None, timeout=30.0):
        """Submit on the worker thread; returns a :class:`StreamHandle`.
        ``trace_args`` (tenant/priority/hop_s from the gateway) are
        appended to the flight record as the ``gateway`` event — on the
        engine thread, so event order stays queued -> gateway ->
        prefill.  Raises whatever ``Engine.submit`` raises (validation)
        or RuntimeError when the replica is draining/stopped."""
        if not self.alive:
            raise WorkerDeadError(f"replica {self.name} is stopped")
        reply = queue.Queue(1)
        self._inbox.put(("submit", dict(
            prompt_ids=prompt_ids, sampling=sampling, priority=priority,
            deadline_s=deadline_s, tenant=tenant, grammar=grammar),
            trace_args, reply))
        kind, value = self._await(reply, timeout)
        if kind == "error":
            raise value
        return value

    def adopt(self, handle, prompt_ids, sampling=None, priority=0,
              tenant=None, grammar=None, resume_ids=(),
              from_replica="", reason="", timeout=30.0):
        """Failover adoption: re-submit a condemned replica's in-flight
        request on THIS worker, resuming from ``resume_ids`` (the
        tokens the client has already received).  On the worker thread
        the engine re-prefills ``prompt + resume_ids`` via the resume
        path — whose bitwise consistency check makes the continuation
        provably seamless — then the handle is re-pointed at the new
        request/worker and tracked for flushing (``handle.sent`` is
        already ``len(resume_ids)``, so only NEW tokens stream)."""
        if not self.alive:
            raise WorkerDeadError(f"replica {self.name} is stopped")
        reply = queue.Queue(1)
        self._inbox.put(("adopt", dict(
            prompt_ids=prompt_ids, sampling=sampling, priority=priority,
            tenant=tenant, grammar=grammar,
            resume_ids=list(resume_ids),
            from_replica=from_replica, reason=reason),
            handle, reply))
        kind, value = self._await(reply, timeout)
        if kind == "error":
            raise value
        return value

    def _await(self, reply, timeout):
        """Wait on a command reply, polling thread aliveness so a
        command racing a crash raises :class:`WorkerDeadError` instead
        of blocking until the timeout."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                return reply.get(timeout=min(0.1, timeout))
            except queue.Empty:
                if not self._thread.is_alive():
                    raise WorkerDeadError(
                        f"replica {self.name} died while processing a "
                        f"command") from None
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"worker {self.name} command timed out")

    def abort(self, handle, cause="client_disconnect"):
        """Abort a tracked request (fire-and-forget; the handle's queue
        still receives its terminal ``("finish", "abort")``).  Routed
        through the handle so an abort issued against a replica the
        request has already failed away from still lands wherever the
        request lives now."""
        handle.abort(cause)

    def drain(self, timeout=120.0):
        """Stop accepting submissions, let in-flight AND queued requests
        run to completion, then ``Engine.drain()`` (releases every pool
        block, asserts the block-leak invariant).  Blocks until done.
        Idempotent; the worker stays alive (for ``stats()``) until
        ``stop()``.  Raises :class:`WorkerDeadError` (not a hang) when
        the engine thread has died — a dead replica cannot drain; its
        streams are the supervisor's to fail over."""
        if not self._thread.is_alive():
            raise WorkerDeadError(
                f"replica {self.name} is dead; cannot drain")
        self._inbox.put(("drain", None, None, None))
        deadline = time.monotonic() + timeout
        while not self._drained.wait(min(0.1, timeout)):
            if not self._thread.is_alive():
                raise WorkerDeadError(
                    f"replica {self.name} died while draining")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"worker {self.name} drain timed out")

    def stop(self, timeout=30.0):
        """Stop the driving thread (does NOT close the engine — the
        owner does, after ``drain()``).  A no-op on a worker whose
        thread already died: there is nothing left to stop, and
        enqueueing to a dead inbox would block callers forever."""
        if self._stopped:
            return
        if not self._thread.is_alive():
            self._stopped = True
            return
        self._inbox.put(("stop", None, None, None))
        self._thread.join(timeout)
        self._stopped = True

    def take_pending(self):
        """Atomically claim every tracked stream (supervisor-only; call
        after condemning the worker, when its thread is dead or blocked
        in an injected stall and can no longer touch ``_pending``).
        Each handle is flagged ``failing_over`` so client aborts racing
        the swap queue behind the re-dispatch decision."""
        pending, self._pending = dict(self._pending), {}
        for h in pending.values():
            with h.lock:
                h.failing_over = True
        return pending

    # -------------------------------------------------------------- health
    @property
    def alive(self):
        return self._thread.is_alive() and not self._stopped

    @property
    def draining(self):
        return self._draining

    @property
    def crashed(self):
        return self._crashed

    @property
    def condemned(self):
        return self._condemned

    @property
    def heartbeat_age_s(self):
        """Seconds since the worker loop last completed an iteration."""
        return time.monotonic() - self._heartbeat

    @property
    def stalled(self):
        """True when the worker holds work but its loop hasn't
        heartbeat within ``watchdog_timeout_s`` — a hung dispatch.  An
        idle worker is never stalled (its heartbeat ticks on every inbox
        poll); ``None`` timeout disables the check."""
        t = self.watchdog_timeout_s
        if t is None or not self._thread.is_alive():
            return False
        return (self.engine.scheduler.has_work
                and self.heartbeat_age_s > float(t))

    @property
    def healthy(self):
        """Routable: thread alive, not draining/condemned/stalled, and
        the engine's SLO tracker (if any) reports healthy — the same
        signal the telemetry server's ``/readyz`` flips on."""
        if (not self.alive or self._draining or self._condemned
                or self.stalled):
            return False
        slo = self.engine.slo
        return slo is None or slo.healthy

    @property
    def load(self):
        """Instantaneous load for least-loaded routing: queued +
        running requests."""
        return (self.engine.scheduler.queue_depth
                + len(self.engine.scheduler.running))

    @property
    def prefix_block_size(self):
        return self.engine._block_size

    def stats(self):
        """The engine's ``stats()`` snapshot plus worker state.  Host
        counters only — safe to call from any thread."""
        s = self.engine.stats()
        s["worker"] = {"name": self.name, "alive": self.alive,
                       "draining": self._draining,
                       "healthy": self.healthy, "load": self.load,
                       "streams": len(self._pending),
                       "crashed": self._crashed,
                       "condemned": self._condemned,
                       "heartbeat_age_s": round(self.heartbeat_age_s, 4),
                       "dispatch_faults": self._dispatch_faults}
        return s

    # ---------------------------------------------------------- the thread
    def _loop(self):
        try:
            self._loop_body()
        except BaseException as e:
            # the thread dies here — injected WorkerCrash, condemnation,
            # or a real engine fault.  Record, close the engine's books
            # (this thread OWNS the engine; the supervisor never touches
            # it), and exit; the supervisor notices (alive flips False)
            # and fails the in-flight streams over.
            self._crashed = True
            self._crash_error = e
            _obs_events.instant("serving.worker_crash", cat="serving",
                                worker=self.name, error=repr(e))
            self._reap_engine()

    def _reap_engine(self):
        """Accounting closure on the way out of a crash: abort every
        request still live on this engine (their traces end in
        ``abort(cause="failover")`` — the supervisor re-dispatches the
        streams from the flushed tokens, not from this engine's state)
        and ``close()`` it, unregistering its ``serving.*`` provider.
        Best-effort: a broken engine may refuse individual aborts."""
        eng = self.engine
        live = list(eng.scheduler.running.values()) + list(
            eng.scheduler.queue)
        for req in live:
            if req.status != FINISHED:
                try:
                    eng.abort(req, cause="failover")
                except Exception:
                    pass
        # the aborts returned every lease, so the radix store's chains
        # are unpinned: reclaim them too, so a dead replica's books
        # read kv_blocks_in_use == 0 instead of a stale nonzero
        try:
            eng.prefix.reclaim(eng.prefix._held)
        except Exception:
            pass
        try:
            eng.close()
        except Exception:
            pass

    def _loop_body(self):
        while True:
            if self._condemned:
                # condemned mid-flight (e.g. a watchdog false positive
                # on a slow compile, or a real hang that eventually
                # returned): the supervisor already claimed our streams,
                # so die like a crash — _loop reaps the engine
                raise WorkerCrash(f"worker {self.name} condemned")
            busy = self.engine.scheduler.has_work
            try:
                cmd = (self._inbox.get_nowait() if busy
                       else self._inbox.get(timeout=0.05))
            except queue.Empty:
                cmd = None
            if cmd is not None and self._apply(cmd):
                return
            # apply everything already queued before paying for a step
            while True:
                try:
                    cmd = self._inbox.get_nowait()
                except queue.Empty:
                    break
                if self._apply(cmd):
                    return
            if self.engine.scheduler.has_work:
                try:
                    if self._faults is not None:
                        spec = self._faults.fire(SITE_WORKER_DISPATCH,
                                                 scope=self.name)
                        if (spec is not None
                                and spec.kind == FAULT_STALL):
                            self._stall()
                    self.engine.step()
                except DispatchFault:
                    # transient device error: the same step retries on
                    # the next iteration — requests see one late horizon
                    self._dispatch_faults += 1
                else:
                    if self._flush():
                        # yield the GIL before the next dispatch so
                        # handler threads woken by the flush get to
                        # write their SSE frames now, not a
                        # switch-interval (~5 ms) later
                        time.sleep(0)
            elif self._draining and not self._drained.is_set():
                self.engine.drain()      # queue empty: releases blocks
                self._drained.set()
            self._heartbeat = time.monotonic()

    def _stall(self):
        """Act out an injected stall: block (heartbeat frozen) until
        the supervisor condemns this worker — then die like a crash,
        having never touched ``_pending`` again — or a test releases
        the valve (``_unstall``)."""
        while not self._condemned and not self._unstall.is_set():
            time.sleep(0.002)
        if self._condemned:
            raise WorkerCrash(
                f"worker {self.name} condemned while stalled")
        self._unstall.clear()

    def _apply(self, cmd):
        """Execute one command on the engine thread; True = stop."""
        self._heartbeat = time.monotonic()
        op, arg, extra, reply = cmd
        if op == "stop":
            return True
        if op == "submit":
            if self._draining:
                reply.put(("error", RuntimeError(
                    f"replica {self.name} is draining")))
                return False
            try:
                if self._faults is not None:
                    self._faults.fire(SITE_WORKER_SUBMIT,
                                      scope=self.name)
                req = self.engine.submit(**arg)
            except Exception as e:
                reply.put(("error", e))
                return False
            if extra and req.trace is not None:
                from ...observability import tracing as _obs_tracing

                req.trace.add(_obs_tracing.GATEWAY, **extra)
            handle = StreamHandle(req, self)
            self._pending[req.request_id] = handle
            reply.put(("ok", handle))
        elif op == "adopt":
            handle = extra
            if self._draining:
                reply.put(("error", RuntimeError(
                    f"replica {self.name} is draining")))
                return False
            # the whole adoption is atomic under the handle lock: an
            # adopt the supervisor gave up on (command timeout against
            # a stalled replica) can still be DELIVERED later — by then
            # a retried adopt has cleared ``failing_over``, and this
            # stale one must decline instead of forking the stream
            # onto two engines
            with handle.lock:
                if not handle.failing_over:
                    reply.put(("error", RuntimeError(
                        f"stale adopt on {self.name}: stream "
                        f"{handle.request_id} already re-homed")))
                    return False
                try:
                    if self._faults is not None:
                        self._faults.fire(SITE_WORKER_SUBMIT,
                                          scope=self.name)
                    req = self.engine.submit(
                        arg["prompt_ids"], sampling=arg["sampling"],
                        priority=arg["priority"], tenant=arg["tenant"],
                        grammar=arg.get("grammar"),
                        resume_ids=arg["resume_ids"])
                except Exception as e:
                    reply.put(("error", e))
                    return False
                if req.trace is not None:
                    from ...observability import tracing as _obs_tracing

                    req.trace.add(_obs_tracing.FAILOVER,
                                  from_replica=arg["from_replica"],
                                  reason=arg["reason"],
                                  resumed_tokens=len(arg["resume_ids"]))
                handle.request = req
                handle.worker = self
                handle.failing_over = False
                handle.failovers += 1
                aborted = handle.abort_requested
            self._pending[req.request_id] = handle
            if aborted:
                # the client hung up while the swap was in flight
                self.engine.abort(req, cause="client_disconnect")
                self._flush()
            reply.put(("ok", handle))
        elif op == "abort":
            handle, cause = arg, extra
            if handle.worker is not self:
                # the request failed away from this replica after the
                # abort was enqueued — re-route through the handle
                handle.abort(cause)
            elif handle.request.status != FINISHED:
                self.engine.abort(handle.request, cause=cause)
                self._flush()
        elif op == "drain":
            self._draining = True
        return False

    def _flush(self):
        """Push each tracked request's newly harvested tokens (and its
        terminal event) into its handle queue — the per-horizon flush
        the SSE stream rides.  Returns True if any event was pushed."""
        done, pushed = [], False
        for rid, h in self._pending.items():
            n = h.request.n_generated
            if n > h.sent:
                h.events.put(("tokens",
                              list(h.request.output_ids[h.sent:n])))
                h.sent = n
                pushed = True
            if h.request.status == FINISHED:
                h.events.put(("finish", h.request.finish_reason))
                done.append(rid)
                pushed = True
        for rid in done:
            del self._pending[rid]
        return pushed


def _rendezvous_weight(key, name):
    """Deterministic highest-random-weight score for (affinity key,
    replica name) — stable across processes (no PYTHONHASHSEED
    dependence), uniform enough that distinct system prompts spread
    over replicas."""
    h = hashlib.blake2b(repr(key).encode() + b"|" + name.encode(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


class PrefixAffinityRouter:
    """Routes requests over a set of :class:`EngineWorker` replicas.

    ``affinity_blocks`` bounds how many leading prefix-cache blocks key
    the session: hashing MORE blocks than the shared system prompt
    would scatter same-prefix sessions (their suffixes differ), hashing
    fewer costs nothing — so the default is small."""

    def __init__(self, workers, affinity_blocks=2, retry=None):
        if not workers:
            raise ValueError("router needs at least one worker")
        self.workers = list(workers)
        self.affinity_blocks = int(affinity_blocks)
        #: RetryPolicy for transient submit failures (None = no retry)
        self.retry = retry
        self._ordinal_lock = threading.Lock()
        self._submit_ordinal = 0

    def next_ordinal(self):
        """Monotonic submit ordinal — the per-request key the retry
        policy's deterministic jitter hashes on."""
        with self._ordinal_lock:
            n = self._submit_ordinal
            self._submit_ordinal += 1
        return n

    def affinity_key(self, prompt_ids):
        """The routing key: the prompt's leading full blocks, chunked
        with the radix cache's block size (``None`` when the prompt is
        shorter than one block — no reusable prefix to be affine to)."""
        bs = self.workers[0].prefix_block_size
        nb = min(self.affinity_blocks, len(prompt_ids) // bs)
        if nb <= 0:
            return None
        return tuple(int(t) for t in prompt_ids[:nb * bs])

    def route(self, prompt_ids):
        """Pick a replica: ``(worker, how)`` where ``how`` is
        ``"affine"`` (rendezvous hash of the prefix key over healthy
        replicas) or ``"least-loaded"`` (no key).  ``(None, "shed")``
        when no replica is healthy — the gateway's 503 signal."""
        live = [w for w in self.workers if w.healthy]
        if not live:
            return None, "shed"
        key = self.affinity_key(prompt_ids)
        if key is None:
            return min(live, key=lambda w: (w.load, w.name)), \
                "least-loaded"
        return max(live,
                   key=lambda w: _rendezvous_weight(key, w.name)), \
            "affine"

    def submit(self, prompt_ids, sampling=None, **kw):
        """Route + submit in one call; returns ``(handle, worker,
        how)`` or raises RuntimeError when every replica is shedding.
        Transient submit failures are retried under :attr:`retry`
        (capped exponential backoff, deterministic jitter), re-routing
        each attempt — a replica that died between route and submit
        just sends the retry elsewhere.  Only a spent budget
        propagates the error."""
        ordinal = self.next_ordinal()
        attempt = 0
        while True:
            worker, how = self.route(prompt_ids)
            if worker is None:
                raise RuntimeError("no healthy replica")
            try:
                return (worker.submit(prompt_ids, sampling=sampling,
                                      **kw), worker, how)
            except (TransientSubmitError, WorkerDeadError,
                    TimeoutError):
                # TimeoutError: the replica stopped answering its inbox
                # (stalled inside its watchdog leash) — as transient as
                # a dead one from the caller's seat
                if self.retry is None or attempt >= self.retry.max_retries:
                    raise
                _SRV_RETRIES.inc(replica=worker.name)
                time.sleep(self.retry.delay(ordinal, attempt))
                attempt += 1

    def remove(self, worker, close_engine=True):
        """Graceful replica removal: stop routing to it, drain it
        (in-flight work finishes, every pool block released), stop its
        thread, and optionally close its engine."""
        self.workers.remove(worker)
        worker.drain()
        worker.stop()
        if close_engine:
            # ownership transferred: drain() emptied it and stop()
            # joined the worker thread — no live thread can touch it
            worker.engine.close()  # noqa: PTA510


class FleetSupervisor:
    """The watchdog + failover loop over a router's workers.

    ``check()`` is one synchronous sweep (what tests drive directly):
    any worker whose thread died, or that is ``stalled`` past
    ``watchdog_timeout_s``, is condemned and its streams failed over.
    ``start()`` runs the sweep on a daemon thread every ``interval_s``
    — what the gateway wires up.

    Condemnation is one-way: the worker is flagged (``healthy`` flips
    False, a blocked stall raises out and the thread dies), and the
    dying thread itself closes its engine's books (in-flight traces
    end in ``abort(cause="failover")``; ``Engine.close()`` unregisters
    its ``serving.*`` telemetry provider — the supervisor never touches
    an engine it doesn't own).  Then each claimed stream is
    re-dispatched: the router
    picks a surviving replica, ``worker.adopt()`` resumes from the
    tokens the client already received, and ``serving.failovers``
    ticks.  A stream whose resume history already terminates (EOS
    sampled / token budget spent — the worker died between harvest and
    flush of the finish) is finished directly instead of re-decoded,
    and a stream whose client hung up mid-swap is dropped — that is
    the cancel path of the pending re-dispatch.

    Failover never reads the condemned engine's state — the new
    replica recomputes from the handle's flushed tokens — so it is
    correct even against a *real* wedged dispatch that keeps host
    state pinned; in that one case the wedged engine's blocks stay
    leaked until process exit, which is what ``condemned`` stats are
    for."""

    def __init__(self, router, watchdog_timeout_s=60.0, interval_s=1.0,
                 retry=None, adopt_timeout_s=10.0):
        self.router = router
        self.watchdog_timeout_s = (None if watchdog_timeout_s is None
                                   else float(watchdog_timeout_s))
        self.interval_s = float(interval_s)
        self.retry = retry or RetryPolicy()
        #: per-attempt adopt command timeout — deliberately shorter
        #: than a worker command timeout, so one stalled-but-not-yet-
        #: condemned adoption target can't wedge the whole sweep
        self.adopt_timeout_s = float(adopt_timeout_s)
        self.failovers = 0           # streams successfully re-dispatched
        self.failover_failures = 0   # streams aborted (no healthy target)
        self.condemned = []          # (worker.name, reason)
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="gateway.supervisor", daemon=True)
        self._thread.start()

    def stop(self, timeout=10.0):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception as e:
                _obs_events.instant("serving.supervisor_error",
                                    cat="serving", error=repr(e))

    # ------------------------------------------------------------- the sweep
    def check(self):
        """One watchdog sweep; returns the workers condemned by it.  A
        worker's own ``watchdog_timeout_s`` (when set) overrides the
        supervisor default — a replica known to run long dispatches can
        carry a longer leash than the fleet."""
        acted = []
        for w in list(self.router.workers):
            if w._condemned or w._stopped:
                continue
            t = w.watchdog_timeout_s
            if t is None:
                t = self.watchdog_timeout_s
            if not w._thread.is_alive():
                self.condemn(w, "crash")
                acted.append(w)
            elif (t is not None and w.engine.scheduler.has_work
                  and w.heartbeat_age_s > float(t)):
                self.condemn(w, "watchdog_stall")
                acted.append(w)
        return acted

    def condemn(self, worker, reason):
        """Remove a dead/hung worker from service and fail its
        in-flight streams over to the survivors."""
        with self._lock:
            if worker._condemned:
                return
            worker._condemned = True
            self.condemned.append((worker.name, reason))
        _obs_events.instant("serving.worker_condemned", cat="serving",
                            worker=worker.name, reason=reason)
        pending = worker.take_pending()
        # NOTE: the supervisor never touches the condemned engine — the
        # worker thread owns it, and tearing it down from here while
        # the thread may still be inside a dispatch corrupts device
        # state.  The thread closes its own books on the way out
        # (``_reap_engine``: in-flight traces end in
        # ``abort(cause="failover")``, ``Engine.close()`` unregisters
        # the serving.* provider); a thread wedged forever in a real
        # hung dispatch leaks its engine until process exit, which is
        # what the ``condemned`` stats are for.
        for h in pending.values():
            self._failover(h, worker, reason)
        return pending

    def _failover(self, handle, from_worker, reason):
        req = handle.request
        sent = int(handle.sent)
        resume = [int(t) for t in req.output_ids[:sent]]
        with handle.lock:
            if handle.abort_requested:
                # client hung up while the replica was dying: cancel
                # the re-dispatch instead of resuming a dead stream
                handle.failing_over = False
                handle.events.put(("finish", FINISH_ABORT))
                return
        # the stream may already be complete from the client's point of
        # view (the worker died after flushing the last token but
        # before the finish event): finish it, don't re-decode
        eos = getattr(req.sampling, "eos_token_id", None)
        if resume and eos is not None and resume[-1] == int(eos):
            self._finish_direct(handle, FINISH_EOS)
            return
        if len(resume) >= req.sampling.max_new_tokens:
            self._finish_direct(handle, FINISH_LENGTH)
            return
        attempt = 0
        ordinal = self.router.next_ordinal()
        while True:
            worker, _how = self.router.route(req.prompt_ids)
            if worker is None:
                self._abort_stream(handle, "failover_no_replica")
                return
            try:
                worker.adopt(handle, prompt_ids=req.prompt_ids,
                             sampling=req.sampling,
                             priority=req.priority, tenant=req.tenant,
                             grammar=req.grammar,
                             resume_ids=resume,
                             from_replica=from_worker.name,
                             reason=reason,
                             timeout=self.adopt_timeout_s)
            except (TransientSubmitError, WorkerDeadError,
                    RuntimeError, TimeoutError):
                # a timed-out adopt may still be delivered later; the
                # worker-side stale-adopt guard declines it, so
                # retrying onto another replica cannot fork the stream
                with handle.lock:
                    if not handle.failing_over:
                        # ... and conversely, a timed-out attempt that
                        # landed anyway re-homed the stream already —
                        # this retry's decline IS that success
                        worker = handle.worker
                        break
                if attempt >= self.retry.max_retries:
                    self._abort_stream(handle, "failover_retry_budget")
                    return
                _SRV_RETRIES.inc(replica=worker.name)
                time.sleep(self.retry.delay(ordinal, attempt))
                attempt += 1
                continue
            break
        with self._lock:
            self.failovers += 1
        _SRV_FAILOVERS.inc(from_replica=from_worker.name,
                           to_replica=worker.name)
        _obs_events.instant("serving.failover", cat="serving",
                            request_id=req.request_id,
                            from_replica=from_worker.name,
                            to_replica=worker.name, reason=reason,
                            resumed_tokens=len(resume))

    def _finish_direct(self, handle, finish_reason):
        with handle.lock:
            handle.failing_over = False
        handle.request.finish_reason = finish_reason
        handle.events.put(("finish", finish_reason))
        with self._lock:
            self.failovers += 1
        _SRV_FAILOVERS.inc(from_replica=handle.worker.name,
                           to_replica="-")

    def _abort_stream(self, handle, why):
        with handle.lock:
            handle.failing_over = False
        handle.events.put(("finish", FINISH_ABORT))
        with self._lock:
            self.failover_failures += 1
        _obs_events.instant("serving.failover_failed", cat="serving",
                            request_id=handle.request.request_id,
                            reason=why)

    def stats(self):
        with self._lock:
            return {"failovers": self.failovers,
                    "failover_failures": self.failover_failures,
                    "condemned": list(self.condemned)}
