"""Serving gateway: the OpenAI-style HTTP/SSE front door over a fleet
of in-process engine replicas.

Three layers (one module each):

* :mod:`.protocol` — stdlib-threaded HTTP server, ``/v1/completions``
  with SSE streaming, structured OpenAI-style errors.
* :mod:`.admission` — per-tenant token-bucket quotas (429) and the SLO
  load-shed decision (503 + Retry-After).
* :mod:`.router` — :class:`EngineWorker` replica threads and
  prefix-affinity (rendezvous-hashed radix-cache-block) routing.
"""

from .admission import TenantQuotas, TokenBucket
from .protocol import Gateway, GatewayConfig
from .router import EngineWorker, PrefixAffinityRouter, StreamHandle

__all__ = [
    "Gateway",
    "GatewayConfig",
    "TenantQuotas",
    "TokenBucket",
    "EngineWorker",
    "PrefixAffinityRouter",
    "StreamHandle",
]
