"""Serving gateway: the OpenAI-style HTTP/SSE front door over a fleet
of in-process engine replicas.

Three layers (one module each):

* :mod:`.protocol` — stdlib-threaded HTTP server, ``/v1/completions``
  with SSE streaming, structured OpenAI-style errors.
* :mod:`.admission` — per-tenant token-bucket quotas (429) and the SLO
  load-shed decision (503 + Retry-After).
* :mod:`.router` — :class:`EngineWorker` replica threads,
  prefix-affinity (rendezvous-hashed radix-cache-block) routing, and
  the :class:`FleetSupervisor` watchdog that condemns dead/hung
  replicas and fails their in-flight streams over (bitwise-seamless
  resume on a surviving replica; see ``paddle_tpu.serving.faults``
  for the deterministic chaos layer that tests it).
"""

from .admission import TenantQuotas, TokenBucket
from .protocol import Gateway, GatewayConfig
from .router import (EngineWorker, FleetSupervisor,
                     PrefixAffinityRouter, StreamHandle)

__all__ = [
    "Gateway",
    "GatewayConfig",
    "TenantQuotas",
    "TokenBucket",
    "EngineWorker",
    "FleetSupervisor",
    "PrefixAffinityRouter",
    "StreamHandle",
]
