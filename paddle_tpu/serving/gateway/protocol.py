"""Gateway protocol layer: the OpenAI-style HTTP/SSE front door.

A stdlib ``ThreadingHTTPServer`` (the telemetry server's pattern — no
framework, no new dependency, ``port=0`` binds ephemeral) exposing:

=====================  ==================================================
route                  behavior
=====================  ==================================================
``POST /v1/completions``  OpenAI-style completion over token ids.
                       ``"stream": true`` answers ``text/event-stream``:
                       one ``data: {...}`` chunk per decode horizon the
                       request rode (the worker flushes token deltas as
                       the engine harvests them), a final chunk carrying
                       ``finish_reason``, then the ``data: [DONE]``
                       sentinel.  Non-streaming answers one JSON body
                       with the full ``token_ids`` and ``usage``.
``GET /v1/models``     the single served model, OpenAI list shape
``GET /healthz``       liveness — 200 while the listener serves
``GET /readyz``        readiness — 503 unless some replica is healthy
``GET /metrics``       Prometheus exposition of the process registry
                       (``gateway.*`` families included)
``GET /``              tiny JSON index
=====================  ==================================================

Errors are structured OpenAI-style bodies
(``{"error": {"message", "type", "code"}}``): **400** malformed/invalid
request, **404** unknown model or route, **429** tenant quota exhausted
(``Retry-After`` = seconds until the bucket refills enough), **503** +
``Retry-After`` while every replica is shedding (the SLO burn signal
``/readyz`` flips on) or draining.

The model serves token ids, not text — requests carry ``"prompt"`` as a
list of ints and responses carry ``"token_ids"`` per choice (an
optional ``detokenize`` callable on the config fills the OpenAI
``"text"`` field).  Request fields map 1:1 onto the engine's
``SamplingParams`` (``max_tokens`` -> ``max_new_tokens``,
``stop_token_id`` -> ``eos_token_id``) plus the gateway-era admission
fields ``priority``, ``deadline_s``, and ``tenant`` (OpenAI's ``user``
is accepted as an alias).  A NEGATIVE ``priority`` selects the offline
batch lane: normalized to one tier (-1), non-streaming only (400
``batch_no_stream`` with ``"stream": true``), preemptible, and exempt
from the scheduler's starvation window — interactive traffic passes
it without bound.  Because the engine's sampling is bitwise
deterministic per ``(seed, token index)``, a streamed completion is
token-for-token identical to in-process ``Engine.run()`` for the same
request — tested both greedy and seeded-stochastic.

Deliberately NOT built (out of scope for an in-process fleet front
door): TLS termination, authentication/authorization, multi-host
routing, request body compression.  Terminate TLS and authenticate in
front of this gateway.
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ...observability import metrics as _obs_metrics
from ...observability.server import PROM_CONTENT_TYPE
from ..engine import Engine
from ..faults import (_SRV_RETRIES, RetryPolicy, TransientSubmitError,
                      WorkerDeadError)
from ..sampling import SamplingParams
from ..scheduler import FINISH_EOS
from ..structured.grammar import GrammarError, GrammarSpec
from .admission import TenantQuotas
from .router import EngineWorker, FleetSupervisor, PrefixAffinityRouter

# gateway.* metric families (labels via kwargs, like serving.*)
_GW_REQS = _obs_metrics.counter(
    "gateway.requests", "HTTP requests handled, by route and status")
_GW_REJECTS = _obs_metrics.counter(
    "gateway.rejections",
    "completions rejected at admission (reason=invalid|model|quota|shed)")
_GW_ROUTED = _obs_metrics.counter(
    "gateway.routed", "sessions routed, by replica and affinity outcome")
_GW_STREAMS = _obs_metrics.counter(
    "gateway.streams", "SSE completion streams opened")
_GW_STREAM_TOKENS = _obs_metrics.counter(
    "gateway.stream_tokens", "tokens flushed over SSE streams")
_GW_TTFT = _obs_metrics.histogram(
    "gateway.ttft_seconds",
    "gateway receive to first streamed token chunk")
_GW_LATENCY = _obs_metrics.histogram(
    "gateway.request_seconds", "gateway receive to completion sent")
# the per-tenant ledger, promoted from stats() to scrapeable metrics:
# tokens mirror the engines' authoritative per-tenant accounting
# (republished at each completion), sheds count this gateway's
# admission rejections (quota + SLO shed + retry-budget) per tenant
_GW_TENANT_TOKENS = _obs_metrics.gauge(
    "gateway.tenant_tokens_served",
    "tokens generated per tenant across the fleet (engine ledger, "
    "republished at completion)")
_GW_TENANT_SHEDS = _obs_metrics.gauge(
    "gateway.tenant_sheds",
    "admission rejections per tenant (quota exhausted, SLO shed, "
    "retry budget spent)")

#: finish_reason wire mapping (OpenAI uses "stop" for EOS)
_FINISH_WIRE = {FINISH_EOS: "stop"}


@dataclass
class GatewayConfig:
    host: str = "127.0.0.1"
    #: 0 binds an ephemeral port (``gateway.port`` reports it)
    port: int = 0
    #: the id ``/v1/models`` advertises; requests naming another model
    #: get 404 model_not_found (absent/null model fields are accepted)
    model_id: str = "paddle-tpu"
    #: per-tenant token-bucket quota: a request costs
    #: ``prompt_tokens + max_tokens``.  None disables quota (no 429s).
    quota_tokens: float | None = None
    #: bucket refill rate; None defaults to ``quota_tokens`` per second
    quota_refill_per_s: float | None = None
    #: Retry-After seconds sent with 503 shed responses
    shed_retry_after_s: float = 1.0
    #: leading radix-cache blocks hashed into the routing affinity key
    affinity_blocks: int = 2
    #: interactive priorities are validated to [0, max_priority] (the
    #: scheduler's starvation bound is reorder_window *
    #: (1 + max_priority)).  NEGATIVE priorities are the offline batch
    #: lane: normalized to -1, non-streaming only, preemptible, and
    #: exempt from the starvation window (interactive traffic passes
    #: without bound)
    max_priority: int = 8
    #: ceiling on one completion's wall time before the gateway aborts
    #: it server-side
    request_timeout_s: float = 120.0
    #: worker watchdog: a replica holding work that hasn't heartbeat
    #: within this is condemned and its streams failed over (None
    #: disables stall detection; dead threads are always detected).
    #: Generous by default — a cold compile must never look like a hang.
    watchdog_timeout_s: float | None = 60.0
    #: how often the fleet supervisor sweeps worker health
    watchdog_interval_s: float = 0.25
    #: per-request budget of submit retries after transient failures;
    #: only a spent budget surfaces a 503 (with the next backoff delay
    #: as an honest Retry-After)
    retry_budget: int = 2
    #: capped-exponential retry backoff: base doubles per attempt up to
    #: the cap, scaled by deterministic (seeded) jitter
    retry_backoff_s: float = 0.05
    retry_backoff_cap_s: float = 1.0
    retry_seed: int = 0
    #: optional ``tokens -> str`` callable filling the OpenAI ``text``
    #: response field; None leaves ``text`` empty (ids only)
    detokenize: object = None


class _Reject(Exception):
    """A structured HTTP error: status + OpenAI-style error body."""

    def __init__(self, status, message, etype, code=None,
                 retry_after=None):
        super().__init__(message)
        self.status = status
        self.etype = etype
        self.code = code
        self.retry_after = retry_after

    def body(self):
        return {"error": {"message": str(self), "type": self.etype,
                          "code": self.code}}

    def headers(self):
        if self.retry_after is None:
            return {}
        # ceil so "retry after 0.3s" never rounds down to "now"
        return {"Retry-After": str(max(1, int(-(-self.retry_after))))}


class Gateway:
    """The HTTP front door over N in-process engine replicas.

    ``engines`` may be Engine instances (wrapped in
    :class:`EngineWorker` replicas named ``replica0..N-1``, owned and
    shut down by the gateway) or pre-built workers (caller-owned).
    ``quotas`` overrides the config-derived :class:`TenantQuotas`
    (tests inject a fake clock this way)."""

    def __init__(self, engines, config=None, quotas=None):
        self.config = config or GatewayConfig()
        if not engines:
            raise ValueError("gateway needs at least one engine")
        self._own_workers = isinstance(engines[0], Engine)
        self.workers = (
            [EngineWorker(e, name=f"replica{i}")
             for i, e in enumerate(engines)]
            if self._own_workers else list(engines))
        for w in self.workers:
            # workers keep an explicit watchdog timeout if the caller
            # set one; otherwise they inherit the gateway's
            if w.watchdog_timeout_s is None:
                w.watchdog_timeout_s = self.config.watchdog_timeout_s
        self.retry = RetryPolicy(
            max_retries=self.config.retry_budget,
            backoff_base_s=self.config.retry_backoff_s,
            backoff_cap_s=self.config.retry_backoff_cap_s,
            seed=self.config.retry_seed)
        self.router = PrefixAffinityRouter(
            self.workers, affinity_blocks=self.config.affinity_blocks,
            retry=self.retry)
        self.supervisor = FleetSupervisor(
            self.router,
            watchdog_timeout_s=self.config.watchdog_timeout_s,
            interval_s=self.config.watchdog_interval_s,
            retry=self.retry)
        self.quotas = quotas if quotas is not None else TenantQuotas(
            self.config.quota_tokens, self.config.quota_refill_per_s)
        self._httpd = None
        self._thread = None
        self._finalizer = None
        self._next_cmpl = 0
        self._cmpl_lock = threading.Lock()
        # gateway-side half of the per-tenant ledger: admission sheds
        # (the engines never see a shed request, so only the gateway
        # can bill it)
        self._tenant_sheds = {}
        self._shed_lock = threading.Lock()

    # ----------------------------------------------------------- lifecycle
    @property
    def running(self):
        return self._httpd is not None

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else None

    def url(self, path="/"):
        return f"http://{self.config.host}:{self.port}{path}"

    def start(self):
        """Bind and serve on a daemon thread; idempotent."""
        if self._httpd is not None:
            return self
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer(
            (self.config.host, int(self.config.port)), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"gateway:{self.port}", daemon=True)
        self._thread.start()
        self.supervisor.start()
        self._finalizer = weakref.finalize(self, _finalize_httpd,
                                           self._httpd)
        return self

    def stop(self):
        """Stop the HTTP listener (workers keep running); idempotent."""
        if self._finalizer is not None:
            self._finalizer.detach()
            self._finalizer = None
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def shutdown(self):
        """Full teardown: stop the listener and the supervisor, drain
        and stop every worker; engines the gateway wrapped itself are
        closed too.  A crashed/condemned replica cannot drain
        (``WorkerDeadError``) — its streams were already failed over,
        so teardown skips it rather than fail."""
        self.stop()
        self.supervisor.stop()
        for w in list(self.workers):
            try:
                w.drain()
            except WorkerDeadError:
                pass
            finally:
                w.stop()
            if self._own_workers:
                # ownership transferred: gateway-built engines, closed
                # only after drain() + stop() joined the worker thread
                w.engine.close()  # noqa: PTA510

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # ------------------------------------------------------------- helpers
    def _cmpl_id(self):
        with self._cmpl_lock:
            self._next_cmpl += 1
            return f"cmpl-{self._next_cmpl}"

    def _text(self, token_ids):
        fn = self.config.detokenize
        return fn(token_ids) if fn is not None else ""

    @staticmethod
    def _wire_reason(reason):
        return _FINISH_WIRE.get(reason, reason)

    # ----------------------------------------------------- tenant ledger
    def _bill_shed(self, tenant):
        """Charge one admission rejection to a tenant and republish its
        ``gateway.tenant_sheds`` gauge."""
        tenant = tenant or ""
        with self._shed_lock:
            n = self._tenant_sheds.get(tenant, 0) + 1
            self._tenant_sheds[tenant] = n
        _GW_TENANT_SHEDS.set(n, tenant=tenant)

    def _publish_tenant_tokens(self, tenant):
        """Republish one tenant's fleet-wide generated-token total
        (the engines' authoritative ledger summed across replicas) as
        the ``gateway.tenant_tokens_served`` gauge."""
        tenant = tenant or ""
        total = 0
        for w in self.workers:
            eng = getattr(w, "engine", None)
            if eng is None:
                continue
            try:
                total += eng.tenant_ledger().get(tenant, {}).get(
                    "tokens_generated", 0)
            except Exception:
                continue     # a crashed replica has nothing to report
        _GW_TENANT_TOKENS.set(total, tenant=tenant)

    def tenant_ledger(self):
        """The fleet-wide per-tenant attainment ledger: the engines'
        per-tenant accounting summed across replicas, plus this
        gateway's admission-shed tally — the hook the fleet replay
        harness aggregates per-tenant attainment from (and the source
        of the ``gateway.tenant_*`` gauges on ``/metrics``)."""
        zero = {"submitted": 0, "finished": 0, "aborted": 0,
                "tokens_generated": 0, "sheds": 0}
        out = {}
        for w in self.workers:
            eng = getattr(w, "engine", None)
            if eng is None:
                continue
            try:
                ledger = eng.tenant_ledger()
            except Exception:
                continue
            for tenant, counts in ledger.items():
                agg = out.setdefault(tenant, dict(zero))
                for k, v in counts.items():
                    agg[k] = agg.get(k, 0) + v
        with self._shed_lock:
            sheds = dict(self._tenant_sheds)
        for tenant, n in sheds.items():
            out.setdefault(tenant, dict(zero))["sheds"] = n
        return out

    # ------------------------------------------------------------ GET side
    def handle_get(self, path):
        """Route one GET; returns (status, content_type, body bytes).
        Socket-free (tests call it directly)."""
        path = path.split("?", 1)[0]
        if path == "/v1/models":
            return 200, "application/json", _js(
                {"object": "list",
                 "data": [{"id": self.config.model_id,
                           "object": "model",
                           "owned_by": "paddle_tpu.serving"}]})
        if path == "/healthz":
            return 200, "text/plain; charset=utf-8", b"ok\n"
        if path == "/readyz":
            replicas = {w.name: {"healthy": w.healthy,
                                 "draining": w.draining,
                                 "load": w.load}
                        for w in self.workers}
            ready = any(r["healthy"] for r in replicas.values())
            return ((200 if ready else 503), "application/json",
                    _js({"ready": ready, "replicas": replicas}))
        if path == "/metrics":
            return (200, PROM_CONTENT_TYPE,
                    _obs_metrics.render_prometheus().encode())
        if path == "/":
            return 200, "application/json", _js(
                {"service": "paddle_tpu.serving.gateway",
                 "endpoints": ["/v1/completions", "/v1/models",
                               "/healthz", "/readyz", "/metrics"]})
        return 404, "application/json", _js(
            {"error": {"message": f"unknown route {path}",
                       "type": "invalid_request_error",
                       "code": "route_not_found"}})

    # ----------------------------------------------------- completion path
    def parse_completion(self, payload):
        """Validate a /v1/completions body into the engine-facing
        request dict; raises :class:`_Reject` (400/404) on anything
        malformed.  Unknown fields are ignored (OpenAI-compatible)."""
        def bad(msg, code=None):
            return _Reject(400, msg, "invalid_request_error", code)

        if not isinstance(payload, dict):
            raise bad("request body must be a JSON object")
        model = payload.get("model")
        if model is not None and model != self.config.model_id:
            raise _Reject(
                404, f"model {model!r} not found (serving "
                f"{self.config.model_id!r})", "invalid_request_error",
                "model_not_found")
        prompt = payload.get("prompt")
        if (not isinstance(prompt, (list, tuple)) or not prompt
                or not all(isinstance(t, int)
                           and not isinstance(t, bool) for t in prompt)):
            raise bad("'prompt' must be a non-empty list of token ids "
                      "(ints) — this gateway serves token ids, not text")
        sp = {}
        for wire, field, typ in (
                ("max_tokens", "max_new_tokens", int),
                ("temperature", "temperature", float),
                ("top_k", "top_k", int),
                ("top_p", "top_p", float),
                ("seed", "seed", int),
                ("stop_token_id", "eos_token_id", int),
                ("eos_token_id", "eos_token_id", int)):
            v = payload.get(wire)
            if v is None:
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise bad(f"'{wire}' must be a number")
            sp[field] = typ(v)
        try:
            sampling = SamplingParams(**sp).validate()
        except ValueError as e:
            raise bad(str(e)) from None
        priority = payload.get("priority", 0)
        if (isinstance(priority, bool) or not isinstance(priority, int)
                or priority > self.config.max_priority):
            raise bad(f"'priority' must be an int <= "
                      f"{self.config.max_priority} (negative = the "
                      f"offline batch lane)")
        if priority < 0:
            # the offline batch lane is one tier: lowest, non-streaming,
            # preemptible, overtaken without bound
            priority = -1
            if payload.get("stream"):
                raise bad("batch-lane requests (priority < 0) cannot "
                          "stream: the lane is preemptible and "
                          "non-interactive — poll the JSON completion "
                          "instead", "batch_no_stream")
        deadline = payload.get("deadline_s")
        if deadline is not None and (
                isinstance(deadline, bool)
                or not isinstance(deadline, (int, float))
                or not deadline > 0):
            raise bad("'deadline_s' must be a positive number")
        tenant = payload.get("tenant", payload.get("user", ""))
        if not isinstance(tenant, str):
            raise bad("'tenant' must be a string")
        stream = payload.get("stream", False)
        if not isinstance(stream, bool):
            raise bad("'stream' must be a boolean")
        # structured generation: OpenAI ``response_format`` (json_schema)
        # or the ``grammar`` extension (regex).  Validation is EAGER —
        # an unsupported grammar 400s HERE (code ``invalid_grammar``,
        # message naming the feature), before anything queues.
        grammar = None
        rf = payload.get("response_format")
        if rf is not None:
            if not isinstance(rf, dict) or not isinstance(
                    rf.get("type"), str):
                raise bad("'response_format' must be an object with a "
                          "string 'type'", "invalid_grammar")
            kind = rf["type"]
            if kind == "json_schema":
                js = rf.get("json_schema")
                if not isinstance(js, dict):
                    raise bad("'response_format.json_schema' must be an "
                              "object", "invalid_grammar")
                # OpenAI nests the schema under "schema"; a bare schema
                # object is accepted too
                schema = js.get("schema", js) if "schema" in js else js
                if not isinstance(schema, dict):
                    raise bad("'response_format.json_schema.schema' "
                              "must be a JSON-schema object",
                              "invalid_grammar")
                try:
                    grammar = GrammarSpec.json_schema(schema)
                except GrammarError as e:
                    raise bad(str(e), "invalid_grammar") from None
            elif kind != "text":
                raise bad(
                    f"unsupported response_format type {kind!r} "
                    "(supported: 'text', 'json_schema')",
                    "invalid_grammar")
        gr = payload.get("grammar")
        if gr is not None:
            if grammar is not None:
                raise bad("'grammar' and a json_schema "
                          "'response_format' are mutually exclusive",
                          "invalid_grammar")
            if isinstance(gr, str):
                pattern = gr
            elif (isinstance(gr, dict) and gr.get("type") == "regex"
                    and isinstance(gr.get("pattern"), str)):
                pattern = gr["pattern"]
            else:
                raise bad("'grammar' must be a regex string or "
                          "{'type': 'regex', 'pattern': '...'}",
                          "invalid_grammar")
            try:
                grammar = GrammarSpec.regex(pattern)
            except GrammarError as e:
                raise bad(str(e), "invalid_grammar") from None
        if grammar is not None and sampling.eos_token_id is None:
            raise bad("grammar-constrained requests require "
                      "'eos_token_id' (or 'stop_token_id'): EOS is "
                      "legal exactly in the grammar's accept states",
                      "invalid_grammar")
        return {"prompt_ids": list(prompt), "sampling": sampling,
                "priority": priority, "deadline_s": deadline,
                "tenant": tenant, "stream": stream, "grammar": grammar}

    def admit_and_route(self, parsed, t_recv):
        """Quota gate then replica routing; returns a submitted
        :class:`StreamHandle`.  Raises :class:`_Reject` with 429
        (quota), 503 (every replica shedding/draining, or the retry
        budget spent on transient submit failures — Retry-After then
        carries the NEXT backoff delay, the honest answer), or 400
        (engine-side validation, e.g. prompt+budget over max_seq_len).
        Transient submit failures (and a replica dying between route
        and submit) are retried up to ``retry_budget`` times with
        capped exponential backoff and deterministic jitter,
        re-routing every attempt."""
        cost = (len(parsed["prompt_ids"])
                + parsed["sampling"].max_new_tokens)
        granted, retry = self.quotas.admit(parsed["tenant"], cost)
        if not granted:
            _GW_REJECTS.inc(reason="quota")
            self._bill_shed(parsed["tenant"])
            raise _Reject(
                429, f"tenant {parsed['tenant']!r} quota exhausted "
                f"({cost} tokens requested)", "tenant_quota_exceeded",
                "quota_exhausted", retry_after=retry)
        ordinal = self.router.next_ordinal()
        attempt = 0
        while True:
            worker, how = self.router.route(parsed["prompt_ids"])
            if worker is None:
                _GW_REJECTS.inc(reason="shed")
                self._bill_shed(parsed["tenant"])
                raise _Reject(
                    503, "every replica is unhealthy (SLO burn) or "
                    "draining; retry shortly", "service_unavailable",
                    "slo_shedding",
                    retry_after=self.config.shed_retry_after_s)
            try:
                handle = worker.submit(
                    parsed["prompt_ids"], sampling=parsed["sampling"],
                    priority=parsed["priority"],
                    deadline_s=parsed["deadline_s"],
                    tenant=parsed["tenant"],
                    grammar=parsed.get("grammar"),
                    trace_args={"tenant": parsed["tenant"],
                                "priority": parsed["priority"],
                                "hop_s": round(
                                    time.monotonic() - t_recv, 6)})
            except ValueError as e:
                _GW_REJECTS.inc(reason="invalid")
                raise _Reject(400, str(e),
                              "invalid_request_error") from None
            except (TransientSubmitError, WorkerDeadError,
                    TimeoutError) as e:
                if attempt >= self.retry.max_retries:
                    _GW_REJECTS.inc(reason="retry_budget")
                    self._bill_shed(parsed["tenant"])
                    raise _Reject(
                        503, f"submit failed after {attempt + 1} "
                        f"attempts: {e}", "service_unavailable",
                        "retry_budget_exhausted",
                        retry_after=self.retry.delay(
                            ordinal, attempt + 1)) from None
                _SRV_RETRIES.inc(replica=worker.name)
                time.sleep(self.retry.delay(ordinal, attempt))
                attempt += 1
                continue
            except RuntimeError as e:
                _GW_REJECTS.inc(reason="shed")
                self._bill_shed(parsed["tenant"])
                raise _Reject(
                    503, str(e), "service_unavailable",
                    "replica_draining",
                    retry_after=self.config.shed_retry_after_s) \
                    from None
            break
        _GW_ROUTED.inc(replica=worker.name, affinity=how)
        return handle

    def _chunk(self, cmpl_id, created, token_ids, reason=None):
        return {"id": cmpl_id, "object": "text_completion.chunk",
                "created": created, "model": self.config.model_id,
                "choices": [{"index": 0, "token_ids": token_ids,
                             "text": self._text(token_ids),
                             "finish_reason": reason}]}

    def sse_events(self, handle, t_recv):
        """Generator of SSE frames (bytes) for one streaming
        completion: one ``data:`` frame per harvested token chunk, a
        final frame carrying ``finish_reason``, then ``data: [DONE]``.
        Timeout aborts the request server-side and surfaces as
        ``finish_reason: "abort"`` — the stream always terminates."""
        cmpl_id = self._cmpl_id()
        created = int(time.time())
        deadline = t_recv + self.config.request_timeout_s
        _GW_STREAMS.inc()
        first = True
        while True:
            try:
                kind, value = handle.events.get(
                    timeout=max(0.05, deadline - time.monotonic()))
            except Exception:
                handle.worker.abort(handle, cause="gateway_timeout")
                kind, value = handle.events.get(timeout=30.0)
                while kind != "finish":      # drain to the terminal
                    kind, value = handle.events.get(timeout=30.0)
            if kind == "tokens":
                if first:
                    _GW_TTFT.observe(time.monotonic() - t_recv)
                    first = False
                _GW_STREAM_TOKENS.inc(len(value))
                yield _sse(self._chunk(cmpl_id, created, value))
            else:
                yield _sse(self._chunk(cmpl_id, created, [],
                                       self._wire_reason(value)))
                yield b"data: [DONE]\n\n"
                _GW_LATENCY.observe(time.monotonic() - t_recv)
                self._publish_tenant_tokens(handle.request.tenant)
                return

    def complete_sync(self, handle, t_recv):
        """Blocking non-streaming completion: wait for the terminal
        event, answer one JSON body."""
        deadline = t_recv + self.config.request_timeout_s
        while True:
            try:
                kind, value = handle.events.get(
                    timeout=max(0.05, deadline - time.monotonic()))
            except Exception:
                handle.worker.abort(handle, cause="gateway_timeout")
                continue
            if kind == "finish":
                break
        req = handle.request
        _GW_LATENCY.observe(time.monotonic() - t_recv)
        self._publish_tenant_tokens(req.tenant)
        return {
            "id": self._cmpl_id(), "object": "text_completion",
            "created": int(time.time()),
            "model": self.config.model_id,
            "choices": [{"index": 0,
                         "token_ids": list(req.output_ids),
                         "text": self._text(req.output_ids),
                         "finish_reason": self._wire_reason(value)}],
            "usage": {"prompt_tokens": req.prompt_len,
                      "completion_tokens": req.n_generated,
                      "total_tokens": (req.prompt_len
                                       + req.n_generated)}}


def _js(obj):
    return (json.dumps(obj, indent=2, default=repr) + "\n").encode()


def _sse(obj):
    """One SSE frame: ``data: <json>`` terminated by a blank line."""
    return b"data: " + json.dumps(obj).encode() + b"\n\n"


def _finalize_httpd(httpd):
    try:
        httpd.shutdown()
        httpd.server_close()
    except Exception:                    # pragma: no cover - interp exit
        pass


def _make_handler(gateway):
    # weakref (the telemetry server's pattern): the serving thread holds
    # the httpd which holds this class — a strong ref would pin an
    # abandoned gateway and its engines alive forever
    ref = weakref.ref(gateway)

    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _respond(self, status, ctype, body, headers=None):
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            gw = ref()
            route = self.path.split("?", 1)[0]
            try:
                if gw is None:
                    raise RuntimeError("gateway shutting down")
                status, ctype, body = gw.handle_get(self.path)
            except Exception as e:   # never kill the serving thread
                status, ctype = 500, "application/json"
                body = _js({"error": {
                    "message": f"{type(e).__name__}: {e}",
                    "type": "internal_error", "code": None}})
            _GW_REQS.inc(route=route, code=str(status))
            self._respond(status, ctype, body)

        def do_POST(self):
            gw = ref()
            t_recv = time.monotonic()
            route = self.path.split("?", 1)[0]
            status = 500
            try:
                if gw is None:
                    raise RuntimeError("gateway shutting down")
                if route != "/v1/completions":
                    raise _Reject(404, f"unknown route {route}",
                                  "invalid_request_error",
                                  "route_not_found")
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(
                        self.rfile.read(length).decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    _GW_REJECTS.inc(reason="invalid")
                    raise _Reject(400, "request body is not valid JSON",
                                  "invalid_request_error") from None
                parsed = gw.parse_completion(payload)
                handle = gw.admit_and_route(parsed, t_recv)
                if parsed["stream"]:
                    status = 200
                    _GW_REQS.inc(route=route, code="200")
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/event-stream; charset=utf-8")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.close_connection = True
                    self.end_headers()
                    try:
                        for frame in gw.sse_events(handle, t_recv):
                            self.wfile.write(frame)
                            self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        handle.worker.abort(handle)
                    return
                status = 200
                body = _js(gw.complete_sync(handle, t_recv))
                _GW_REQS.inc(route=route, code="200")
                self._respond(200, "application/json", body)
            except _Reject as e:
                status = e.status
                _GW_REQS.inc(route=route, code=str(status))
                self._respond(status, "application/json", _js(e.body()),
                              headers=e.headers())
            except Exception as e:   # never kill the serving thread
                _GW_REQS.inc(route=route, code=str(status))
                self._respond(500, "application/json", _js(
                    {"error": {"message": f"{type(e).__name__}: {e}",
                               "type": "internal_error", "code": None}}))

        def log_message(self, fmt, *args):
            pass                     # high-frequency; keep stderr quiet

    return _Handler
