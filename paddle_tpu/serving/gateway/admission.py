"""Gateway admission layer: per-tenant token-bucket quotas and
load-shedding decisions.

The gateway admits a request through two independent gates before any
engine sees it:

* **Quota** — every tenant owns a token bucket; a request costs
  ``prompt_tokens + max_tokens`` (the engine bills the same unit in its
  per-tenant ``stats()['tenants']`` accounting, so the quota currency
  and the usage ledger agree).  An empty bucket is a **429** with a
  ``Retry-After`` telling the client exactly when the bucket will hold
  enough tokens again.
* **SLO shed** — the router exposes per-replica health derived from
  each engine's :class:`~paddle_tpu.observability.slo.SLOTracker` (the
  very signal ``/readyz`` flips on).  When NO replica is healthy the
  gateway sheds with **503 + Retry-After** instead of queueing more
  work onto a fleet that is already burning its error budget.

Everything here is pure host-side bookkeeping with an injectable clock
(``clock=...``), so the refill math is exactly testable without
sleeping.
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    """A classic token bucket: ``capacity`` tokens, refilled at
    ``refill_per_s`` tokens per second, lazily on access (no timer
    thread).  ``try_take(n)`` either debits ``n`` and grants, or
    denies with the seconds until the bucket will hold ``n`` again.

    Thread-safe: gateway handler threads race on the same tenant's
    bucket."""

    def __init__(self, capacity, refill_per_s, clock=time.monotonic):
        if not capacity > 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        if not refill_per_s > 0:
            raise ValueError(
                f"refill_per_s must be > 0, got {refill_per_s}")
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._clock = clock
        self._tokens = float(capacity)
        self._last = clock()
        self._lock = threading.Lock()

    def _refill(self):
        now = self._clock()
        self._tokens = min(self.capacity,
                           self._tokens
                           + (now - self._last) * self.refill_per_s)
        self._last = now

    @property
    def available(self):
        with self._lock:
            self._refill()
            return self._tokens

    def try_take(self, n):
        """Attempt to debit ``n`` tokens.  Returns ``(granted,
        retry_after_s)``: ``(True, 0.0)`` on success, ``(False, s)``
        where ``s`` is the time until ``min(n, capacity)`` tokens will
        be available (a request larger than the whole bucket can never
        be granted; the retry hint then points at a full bucket)."""
        n = float(n)
        with self._lock:
            self._refill()
            if n <= self._tokens:
                self._tokens -= n
                return True, 0.0
            need = min(n, self.capacity) - self._tokens
            return False, need / self.refill_per_s


class TenantQuotas:
    """Per-tenant token buckets under one default quota, with optional
    per-tenant overrides (:meth:`set_quota`).  With ``capacity=None``
    quota enforcement is off and every request is granted — the
    gateway's default, so a bare ``Gateway(engines)`` never 429s.

    Buckets are created lazily on a tenant's first request; the empty
    string is the bucket anonymous requests (no ``tenant``/``user``
    field) bill against, matching the engine's accounting key."""

    def __init__(self, capacity=None, refill_per_s=None,
                 clock=time.monotonic):
        if capacity is not None and refill_per_s is None:
            # sensible default: a full bucket refills in one second
            refill_per_s = capacity
        self._capacity = capacity
        self._refill = refill_per_s
        self._clock = clock
        self._buckets = {}
        self._overrides = {}
        self._lock = threading.Lock()

    @property
    def enforcing(self):
        return self._capacity is not None or bool(self._overrides)

    def set_quota(self, tenant, capacity, refill_per_s=None):
        """Give ``tenant`` its own bucket (replacing any existing one,
        full)."""
        with self._lock:
            self._overrides[tenant] = (capacity,
                                       refill_per_s or capacity)
            self._buckets.pop(tenant, None)

    def _bucket(self, tenant):
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                cap, refill = self._overrides.get(
                    tenant, (self._capacity, self._refill))
                if cap is None:
                    return None
                b = TokenBucket(cap, refill, clock=self._clock)
                self._buckets[tenant] = b
            return b

    def admit(self, tenant, cost):
        """Charge ``cost`` tokens to ``tenant``; returns ``(granted,
        retry_after_s)``.  Unquota'd tenants are always granted."""
        bucket = self._bucket(tenant)
        if bucket is None:
            return True, 0.0
        return bucket.try_take(cost)
