"""Slotted, static-shape KV cache for continuous-batching decode.

The legacy decode path in models/gpt.py grows a `(k, v)` concat cache by
one position per step, so every step has a new shape and eager decode
retraces constantly (DECODE_BENCH.json: ~2.6 ms/token against a 0.77 ms
weight roofline). The serving cache instead preallocates per-layer
``[num_slots, max_seq_len, kv_heads, head_dim]`` buffers and writes each
new token in place via ``lax.dynamic_update_slice`` — one compiled decode
step serves every step of every request mix with zero retracing.

Two layers of API:

* :class:`SlotKV` — the per-layer *view* a model forward sees: the slot
  rows it attends over (``k``/``v``, batch-major) plus the per-row write
  position ``pos``.  models/gpt.py's attention accepts it anywhere the
  legacy ``(k, v)`` tuple cache is accepted.
* :class:`SlottedKVCache` — the engine-side owner of the full per-layer
  buffers and the slot free-list.

All helpers are pure jnp functions so they trace into one XLA program.

Horizon-scan contract (engine.py fused decode): the engine advances all
slots H steps inside one ``lax.scan``, and lanes that hit EOS/max-tokens
mid-horizon are *frozen* — their ``pos`` stops advancing — but the scan
body still issues a ``write_slots`` for every lane every step.  A frozen
lane therefore keeps rewriting the same row position with garbage.  That
is safe by construction: the row's visible window is bounded by ``pos``
(``visible_mask``), so the garbage is never attended over, and prefill
overwrites the full ``max_seq_len`` row before a freed slot is reused.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass
class SlotKV:
    """One layer's slotted-cache view for a batch of slot rows.

    k, v: [batch, max_seq_len, kv_heads, head_dim] cache buffers
    pos:  [batch] int32 — the write position per row (== number of tokens
          already cached in that row); the incoming tokens are written at
          positions pos .. pos+s-1 and attend over keys 0 .. pos+s-1.
    """

    k: jax.Array
    v: jax.Array
    pos: jax.Array

    @property
    def max_seq_len(self):
        return self.k.shape[1]


def write_slots(cache, new, pos):
    """Write ``new`` [B, s, H, D] into ``cache`` [B, S_max, H, D] at
    per-row positions ``pos`` [B] via dynamic_update_slice (in-place in
    HBM under jit when the buffer is donated)."""
    def upd(c, n, p):
        return jax.lax.dynamic_update_slice(
            c, n.astype(c.dtype), (p.astype(jnp.int32), 0, 0))

    return jax.vmap(upd)(cache, new, pos)


def visible_mask(pos, s, max_seq_len):
    """Boolean attention mask [B, 1, s, S_max]: query i of row b (absolute
    position pos[b]+i) sees cache keys at positions <= pos[b]+i.  Padded
    prompt tail and stale tokens from a previous slot occupant sit at
    positions >= the row's current length, so they are always masked."""
    q_pos = pos[:, None] + jnp.arange(s, dtype=pos.dtype)        # [B, s]
    key_idx = jnp.arange(max_seq_len, dtype=pos.dtype)           # [S_max]
    return key_idx[None, None, None, :] <= q_pos[:, None, :, None]


class SlottedKVCache:
    """Engine-owned per-layer slotted buffers + the slot free-list.

    The arrays live as plain jax arrays (not Tensors) so the engine can
    pass them straight into its jitted prefill/decode programs and donate
    them for in-place updates.
    """

    def __init__(self, num_layers, num_slots, max_seq_len, kv_heads,
                 head_dim, dtype=jnp.float32):
        self.num_layers = num_layers
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        shape = (num_slots, max_seq_len, kv_heads, head_dim)
        self.k = [jnp.zeros(shape, dtype) for _ in range(num_layers)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(num_layers)]
        self._free = list(range(num_slots - 1, -1, -1))

    # ---------------- slot bookkeeping (host side)
    def alloc(self):
        """Claim a free slot index, or None when the cache is full."""
        return self._free.pop() if self._free else None

    def free(self, slot):
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self._free.append(slot)

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def used_slots(self):
        return self.num_slots - len(self._free)

    def layer_views(self, pos):
        """Per-layer SlotKV views over ALL slots (the fused decode step
        runs every slot; inactive rows are masked by their pos)."""
        return [SlotKV(self.k[i], self.v[i], pos)
                for i in range(self.num_layers)]

    def rebind(self, new_k, new_v):
        """Adopt updated buffers returned by a jitted program."""
        self.k = list(new_k)
        self.v = list(new_v)
