"""KV caches for continuous-batching decode: the slotted static-shape
cache and the unified paged block pool.

The legacy decode path in models/gpt.py grows a `(k, v)` concat cache by
one position per step, so every step has a new shape and eager decode
retraces constantly (DECODE_BENCH.json: ~2.6 ms/token against a 0.77 ms
weight roofline).  Two static-shape cache designs fix that:

* **Slotted rows** (:class:`SlottedKVCache` + :class:`SlotKV`) — one
  per-layer ``[num_slots, max_seq_len, kv_heads, head_dim]`` buffer,
  written in place via ``lax.dynamic_update_slice``.  Simple, but every
  decode step attends position-masked over the FULL ``max_seq_len`` row,
  so short sequences pay bandwidth for the whole row, and a separate
  prefix-cache pool needs per-admission gathers to bridge the two
  allocations.
* **Paged pool** (:class:`PagedKVPool` + :class:`PagedKV`) — ONE
  per-layer ``[num_blocks, block_size, kv_heads, head_dim]`` pool
  (vLLM-style fixed blocks) shared by every slot AND the prefix cache,
  addressed through a per-slot block table.  Decode attention reads only
  the table-mapped blocks below each row's length (ragged), prefix hits
  lease cached blocks straight into a slot's table (copy-free,
  refcounted), and preempting an idle sequence is just releasing its
  table entries.  This is the serving engine's cache since the unified-
  pool refactor; the slotted classes remain for the model-level parity
  tests and as the simpler reference design.

All device-side helpers are pure jnp functions so they trace into one
XLA program.

Horizon-scan contract (engine.py fused decode): the engine advances all
slots H steps inside one ``lax.scan``, and lanes that hit EOS/max-tokens
mid-horizon are *frozen* — their ``pos`` stops advancing — but the scan
body still issues a cache write for every lane every step.  A frozen
lane keeps rewriting the same position with garbage.  That is safe by
construction: the garbage lands at exactly the position the next real
write will overwrite first (decode writes before it attends), everything
written is finite, and the row's visible window is bounded by ``pos``.
After a slot retires, the engine zeroes its block-table row, so any
further masked-lane writes land in the reserved scratch block 0 — slot
reuse never depends on overwriting stale rows, the freed blocks simply
return to the pool.  (The slotted cache relied on the analogous masking
argument: stale row positions sit at indices >= the new occupant's
length until prefill re-writes them.)
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp


@dataclass
class SlotKV:
    """One layer's slotted-cache view for a batch of slot rows.

    k, v: [batch, max_seq_len, kv_heads, head_dim] cache buffers
    pos:  [batch] int32 — the write position per row (== number of tokens
          already cached in that row); the incoming tokens are written at
          positions pos .. pos+s-1 and attend over keys 0 .. pos+s-1.
    """

    k: jax.Array
    v: jax.Array
    pos: jax.Array

    @property
    def max_seq_len(self):
        return self.k.shape[1]


def write_slots(cache, new, pos):
    """Write ``new`` [B, s, H, D] into ``cache`` [B, S_max, H, D] at
    per-row positions ``pos`` [B] via dynamic_update_slice (in-place in
    HBM under jit when the buffer is donated)."""
    def upd(c, n, p):
        return jax.lax.dynamic_update_slice(
            c, n.astype(c.dtype), (p.astype(jnp.int32), 0, 0))

    return jax.vmap(upd)(cache, new, pos)


def visible_mask(pos, s, max_seq_len):
    """Boolean attention mask [B, 1, s, S_max]: query i of row b (absolute
    position pos[b]+i) sees cache keys at positions <= pos[b]+i.  Padded
    prompt tail and stale tokens from a previous slot occupant sit at
    positions >= the row's current length, so they are always masked."""
    q_pos = pos[:, None] + jnp.arange(s, dtype=pos.dtype)        # [B, s]
    key_idx = jnp.arange(max_seq_len, dtype=pos.dtype)           # [S_max]
    return key_idx[None, None, None, :] <= q_pos[:, None, :, None]


class SlottedKVCache:
    """Engine-owned per-layer slotted buffers + the slot free-list.

    The arrays live as plain jax arrays (not Tensors) so the engine can
    pass them straight into its jitted prefill/decode programs and donate
    them for in-place updates.
    """

    def __init__(self, num_layers, num_slots, max_seq_len, kv_heads,
                 head_dim, dtype=jnp.float32):
        self.num_layers = num_layers
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        shape = (num_slots, max_seq_len, kv_heads, head_dim)
        self.k = [jnp.zeros(shape, dtype) for _ in range(num_layers)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(num_layers)]
        self._free = list(range(num_slots - 1, -1, -1))

    # ---------------- slot bookkeeping (host side)
    def alloc(self):
        """Claim a free slot index, or None when the cache is full."""
        return self._free.pop() if self._free else None

    def free(self, slot):
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self._free.append(slot)

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def used_slots(self):
        return self.num_slots - len(self._free)

    def layer_views(self, pos):
        """Per-layer SlotKV views over ALL slots (the fused decode step
        runs every slot; inactive rows are masked by their pos)."""
        return [SlotKV(self.k[i], self.v[i], pos)
                for i in range(self.num_layers)]

    def rebind(self, new_k, new_v):
        """Adopt updated buffers returned by a jitted program."""
        self.k = list(new_k)
        self.v = list(new_v)


# --------------------------------------------------------------- paged

@dataclass
class PagedKV:
    """One layer's paged-cache view for a batch of lanes.

    k, v:    [num_blocks, block_size, kv_heads, head_dim] — the layer's
             slice of the unified pool (block 0 is reserved scratch)
    tables:  [batch, nb] int32 block table — entry j maps token
             positions ``j*block_size .. (j+1)*block_size-1`` of a lane
             to a pool block; 0 marks an unallocated entry (scratch)
    pos:     [batch] int32 — tokens already cached per lane; incoming
             tokens are written at positions pos .. pos+s-1 and attend
             over keys 0 .. pos+s-1 (ragged: only the table-mapped
             blocks are ever read)
    k_scale, v_scale:  [num_blocks, block_size] f32, only when the pool
             stores quantized blocks: the per-token dequantization step
             written beside each int8 token by ``paged_write_quant``;
             None on the fp path (attention then skips dequant).
    """

    k: jax.Array
    v: jax.Array
    tables: jax.Array
    pos: jax.Array
    k_scale: jax.Array = None
    v_scale: jax.Array = None

    @property
    def block_size(self):
        return self.k.shape[1]


def _write_coords(bs, s, tables, pos):
    """Per-token (block, offset) scatter coordinates [B, s] for a write
    of ``s`` tokens at per-lane positions ``pos`` through ``tables``.
    Positions past the table's coverage — padding lanes, frozen lanes
    whose table row was zeroed, write positions in not-yet-allocated
    entries — resolve to block 0 (scratch), where colliding garbage
    writes are harmless by convention."""
    tpos = pos[:, None] + jnp.arange(s, dtype=pos.dtype)         # [B, s]
    blk_idx = tpos // bs
    in_range = blk_idx < tables.shape[1]
    blk_idx = jnp.clip(blk_idx, 0, tables.shape[1] - 1)
    blocks = jnp.take_along_axis(tables, blk_idx, axis=1)        # [B, s]
    blocks = jnp.where(in_range, blocks, 0)
    return blocks, tpos % bs


def paged_write(pool, new, tables, pos):
    """Scatter ``new`` [B, s, H, D] into the paged ``pool``
    [NB, bs, H, D] at per-lane positions ``pos`` [B] through the block
    ``tables`` [B, nb] (out-of-coverage writes land in scratch — see
    :func:`_write_coords`)."""
    bs = pool.shape[1]
    b, s = new.shape[0], new.shape[1]
    blocks, offs = _write_coords(bs, s, tables, pos)
    flat = new.astype(pool.dtype).reshape((b * s,) + new.shape[2:])
    return pool.at[blocks.reshape(-1), offs.reshape(-1)].set(flat)


#: symmetric int8 range used for quantized KV blocks
KV_QMAX = 127.0


def paged_write_quant(pool, scales, new, tables, pos, axis_name=None):
    """Quantize-at-append: scatter ``new`` [B, s, H, D] into the int8
    ``pool`` [NB, bs, H, D] with one f32 absmax scale per TOKEN written
    beside it in ``scales`` [NB, bs].

    The scale granularity is per block-position, not per block: decode
    appends one token at a time, so a coarser per-block scale would have
    to requantize every already-written position of the block whenever a
    new token raised the block's absmax — making stored bytes (and
    therefore attention output) depend on append timing.  Per-token
    quantization is write-once: a token's stored bytes are a pure
    function of its own k/v vector, which preserves the engine's
    bitwise invariants (horizon partitioning, prefill-vs-decode replay
    on preemption resume, prefix-block sharing) within a quant config.
    The cost is 4 bytes per token against ``kv_heads*head_dim`` int8
    payload bytes.

    The per-token floor (``maximum(absmax, 1e-8)``) makes all-zero
    vectors — scratch writes, padding lanes — quantize to exact zeros,
    matching the fp pool's zero-initialized blocks.

    ``axis_name``: inside a shard_map where the head axis (H) is split
    over a mesh axis, pass that axis name and the per-token absmax is
    ``pmax``ed across shards before quantizing.  max is exact
    (associative, no rounding), so the scale equals the full-head
    absmax a single chip would compute and the stored int8 bytes of
    each shard's head slice match the single-chip pool bitwise."""
    bs = pool.shape[1]
    b, s = new.shape[0], new.shape[1]
    blocks, offs = _write_coords(bs, s, tables, pos)
    x = new.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=(2, 3))                    # [B, s]
    if axis_name is not None:
        absmax = jax.lax.pmax(absmax, axis_name)
    step = jnp.maximum(absmax, 1e-8) / KV_QMAX
    q = jnp.clip(jnp.round(x / step[..., None, None]),
                 -KV_QMAX, KV_QMAX)
    flat = q.astype(pool.dtype).reshape((b * s,) + new.shape[2:])
    bi, oi = blocks.reshape(-1), offs.reshape(-1)
    new_pool = pool.at[bi, oi].set(flat)
    new_scales = scales.at[bi, oi].set(
        step.reshape(-1).astype(scales.dtype))
    return new_pool, new_scales


class PagedKVPool:
    """The unified refcounted block pool: per layer, ONE
    ``[num_blocks, block_size, kv_heads, head_dim]`` k/v buffer pair
    shared by every slot's block table and the prefix cache.

    Block 0 is permanently reserved scratch (padding lanes and
    out-of-coverage writes target it).  Every other block is tracked by
    a host-side refcount: a slot-table entry and a prefix-store node
    each hold one reference; a block returns to the free list when the
    last reference is released — which is what makes prefix sharing
    copy-free and preemption just bookkeeping.

    ``quant_dtype="int8"`` switches block storage to int8 with a
    per-layer ``[num_blocks, block_size]`` f32 scale array beside each
    k/v buffer (``paged_write_quant`` fills both; attention dequantizes
    after the gather).  All block bookkeeping — refcounts, leasing,
    COW, preemption — is unchanged: it moves block ids, not bytes."""

    def __init__(self, num_layers, num_blocks, block_size, kv_heads,
                 head_dim, dtype=jnp.float32, quant_dtype=None):
        if num_blocks < 2:
            raise ValueError("paged pool needs >= 2 blocks (one scratch)")
        if quant_dtype not in (None, "int8"):
            raise ValueError(
                f"unsupported KV quant_dtype {quant_dtype!r} "
                "(supported: None, 'int8')")
        self.num_layers = num_layers
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.quant_dtype = quant_dtype
        store_dtype = jnp.int8 if quant_dtype else dtype
        self.store_dtype = store_dtype
        shape = (num_blocks, block_size, kv_heads, head_dim)
        self.k = [jnp.zeros(shape, store_dtype) for _ in range(num_layers)]
        self.v = [jnp.zeros(shape, store_dtype) for _ in range(num_layers)]
        if quant_dtype:
            # zero scales dequantize the zero-initialized blocks to the
            # exact 0.0 the fp pool starts with
            sshape = (num_blocks, block_size)
            self.k_scale = [jnp.zeros(sshape, jnp.float32)
                            for _ in range(num_layers)]
            self.v_scale = [jnp.zeros(sshape, jnp.float32)
                            for _ in range(num_layers)]
        else:
            self.k_scale = self.v_scale = None
        self._refs = np.zeros(num_blocks, np.int32)
        self._refs[0] = 1                    # scratch: pinned forever
        self._free = list(range(num_blocks - 1, 0, -1))

    @property
    def capacity(self):
        """Allocatable blocks (excludes the scratch block)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def blocks_in_use(self):
        return self.capacity - len(self._free)

    @property
    def bytes_per_block(self):
        """ACTUAL device bytes per block across k+v and every layer:
        payload at the storage dtype plus, when quantized, the 4-byte
        f32 scale stored beside each token — the figure the engine's
        ``serving.kv_bytes_read`` accounting multiplies, so quant bench
        numbers come from real bytes, not an fp-equivalent estimate."""
        token_bytes = (self.kv_heads * self.head_dim
                       * jnp.dtype(self.store_dtype).itemsize)
        if self.quant_dtype:
            token_bytes += jnp.dtype(jnp.float32).itemsize
        return 2 * self.num_layers * self.block_size * token_bytes

    def alloc(self):
        """Claim a free block (refcount 1), or None when exhausted."""
        if not self._free:
            return None
        bid = self._free.pop()
        self._refs[bid] = 1
        return bid

    def share(self, block_id):
        """Take one more reference on a live block (prefix lease into a
        slot table, radix-store adoption of a slot's block)."""
        if self._refs[block_id] <= 0:
            raise ValueError(f"block {block_id} shared while free")
        self._refs[block_id] += 1

    def release(self, block_id):
        """Drop one reference; the block returns to the free list when
        the last holder lets go.  Block 0 (scratch) is never released."""
        if block_id == 0:
            return
        if self._refs[block_id] <= 0:
            raise ValueError(f"block {block_id} over-released")
        self._refs[block_id] -= 1
        if self._refs[block_id] == 0:
            self._free.append(block_id)

    def refcount(self, block_id):
        return int(self._refs[block_id])

    def rebind(self, new_k, new_v, new_k_scale=None, new_v_scale=None):
        """Adopt updated pool buffers returned by a jitted program
        (scale buffers ride along on the quantized path; fp-path callers
        may pass the program's ``None`` placeholders back unchanged)."""
        self.k = list(new_k)
        self.v = list(new_v)
        if self.quant_dtype:
            self.k_scale = list(new_k_scale)
            self.v_scale = list(new_v_scale)


class PagedKVCache:
    """Engine-side owner of the paged serving cache: the unified pool,
    the per-slot block tables, and the slot free-list.

    The block table is host-authoritative (``tables`` np array, one row
    per slot, ``max_blocks_per_slot`` entries); the engine uploads the
    live prefix of each row before a dispatch whenever ``tables_dirty``
    is set.  Entries are filled lazily: admission covers the prompt,
    ``ensure_blocks`` extends coverage to each horizon's write window,
    and retirement releases every entry back to the pool."""

    def __init__(self, num_layers, num_slots, max_seq_len, block_size,
                 kv_heads, head_dim, dtype=jnp.float32, num_blocks=0,
                 extra_blocks=0, quant_dtype=None):
        self.num_layers = num_layers
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.block_size = block_size
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.max_blocks_per_slot = -(-max_seq_len // block_size)
        if num_blocks <= 0:
            # auto: every slot can grow to a full row, plus headroom for
            # the prefix store, plus the scratch block
            num_blocks = (1 + num_slots * self.max_blocks_per_slot
                          + extra_blocks)
        self.pool = PagedKVPool(num_layers, num_blocks, block_size,
                                kv_heads, head_dim, dtype,
                                quant_dtype=quant_dtype)
        self.tables = np.zeros((num_slots, self.max_blocks_per_slot),
                               np.int32)
        self.tables_dirty = True
        self._free = list(range(num_slots - 1, -1, -1))

    # ---------------- slot bookkeeping (host side)
    def alloc(self):
        """Claim a free slot index, or None when every slot is taken."""
        return self._free.pop() if self._free else None

    def free(self, slot):
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self._free.append(slot)

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def used_slots(self):
        return self.num_slots - len(self._free)

    # ---------------- block-table bookkeeping (host side)
    def lease_block(self, slot, index, block_id):
        """Map a SHARED pool block (a prefix-cache hit) into a slot's
        table: the table entry takes its own reference."""
        self.pool.share(block_id)
        self.tables[slot, index] = block_id
        self.tables_dirty = True

    def alloc_entry(self, slot, index):
        """Fill one table entry with a fresh private block; returns the
        block id or None when the pool is exhausted."""
        bid = self.pool.alloc()
        if bid is None:
            return None
        self.tables[slot, index] = bid
        self.tables_dirty = True
        return bid

    def ensure_blocks(self, slot, n_tokens):
        """Extend a slot's table to cover ``n_tokens`` positions
        (lazily: only entries still 0 are allocated).  Returns False —
        with any partial allocation kept, it stays valid coverage — when
        the pool runs dry; the engine then reclaims or preempts."""
        need = min(-(-n_tokens // self.block_size),
                   self.max_blocks_per_slot)
        for j in range(need):
            if self.tables[slot, j] == 0:
                if self.alloc_entry(slot, j) is None:
                    return False
        return True

    def release_slot_blocks(self, slot):
        """Release every table entry of a slot (retirement/preemption):
        shared blocks survive while other holders remain; private ones
        return to the pool.  The zeroed row routes any still-in-flight
        masked-lane writes to scratch."""
        row = self.tables[slot]
        for j in np.nonzero(row)[0]:
            self.pool.release(int(row[j]))
        row[:] = 0
        self.tables_dirty = True

    @property
    def leased_blocks(self):
        """Live (slot, entry) references across all block tables."""
        return int(np.count_nonzero(self.tables))

    def layer_views(self, tables, pos):
        """Per-layer PagedKV views over device arrays ``tables``/``pos``
        (the fused decode step runs every slot; inactive lanes are
        masked by their pos and write through zeroed table rows into
        scratch)."""
        ks = self.pool.k_scale or [None] * self.num_layers
        vs = self.pool.v_scale or [None] * self.num_layers
        return [PagedKV(self.pool.k[i], self.pool.v[i], tables, pos,
                        ks[i], vs[i])
                for i in range(self.num_layers)]

    def rebind(self, new_k, new_v, new_k_scale=None, new_v_scale=None):
        """Adopt updated pool buffers returned by a jitted program."""
        self.pool.rebind(new_k, new_v, new_k_scale, new_v_scale)
