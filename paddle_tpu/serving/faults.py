"""Deterministic fault injection for the serving stack.

Chaos testing is only useful if a failing run can be replayed exactly —
so nothing in this module is keyed to a wall clock.  A
:class:`FaultPlan` schedules faults by **site-visit ordinals**: "the
3rd time replica0's worker loop reaches its dispatch site, crash it".
The ordinal counters live in the :class:`FaultInjector` and advance
once per visit, so the same plan against the same workload fires the
same faults at the same logical points, every run, and
:meth:`FaultPlan.chaos` derives a whole schedule from a single seed.

Injection sites (each a named point the serving code calls
:meth:`FaultInjector.fire` from):

* ``worker.dispatch`` — the worker loop, immediately before
  ``engine.step()``.  Supports ``crash`` (the worker thread dies, as if
  the process segfaulted), ``exception`` (one dispatch raises and is
  retried — a transient device error), and ``stall`` (the thread
  blocks, as if a collective hung — only the watchdog can notice).
* ``worker.submit`` — the submit/adopt command on the worker thread.
  Supports ``submit_fail`` (a :class:`TransientSubmitError` the
  router's retry/backoff path absorbs).
* ``engine.admit`` — the engine's admission pass.  Supports
  ``pool_exhausted`` (one admission pass behaves as if the KV block
  pool were dry: the batch is deferred to the next horizon boundary).

Every fired fault ticks ``serving.faults_injected{site,kind}`` and
lands in the process event ring, so a chaos run's injected faults
reconcile against the failovers/retries they caused.

The module also owns the fault-adjacent plumbing shared by the router
and gateway: the typed errors (:class:`WorkerCrash`,
:class:`DispatchFault`, :class:`TransientSubmitError`,
:class:`WorkerDeadError`) and :class:`RetryPolicy` — capped exponential
backoff whose jitter is a pure function of ``(seed, ordinal, attempt)``
(blake2b, not ``random``), so retry timing is replayable too.
"""

from __future__ import annotations

import hashlib
import random
import threading
from dataclasses import dataclass, field

from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

# ------------------------------------------------------------------ kinds
FAULT_CRASH = "crash"                  # worker thread dies
FAULT_EXCEPTION = "exception"          # one dispatch raises, retried
FAULT_STALL = "stall"                  # worker thread hangs (watchdog bait)
FAULT_SUBMIT_FAIL = "submit_fail"      # transient submit failure (retried)
FAULT_POOL_EXHAUSTED = "pool_exhausted"  # one admission pass sees a dry pool

# ------------------------------------------------------------------ sites
SITE_WORKER_DISPATCH = "worker.dispatch"
SITE_WORKER_SUBMIT = "worker.submit"
SITE_ENGINE_ADMIT = "engine.admit"

#: which kinds are meaningful at which site
SITE_KINDS = {
    SITE_WORKER_DISPATCH: (FAULT_CRASH, FAULT_EXCEPTION, FAULT_STALL),
    SITE_WORKER_SUBMIT: (FAULT_SUBMIT_FAIL,),
    SITE_ENGINE_ADMIT: (FAULT_POOL_EXHAUSTED,),
}

# ----------------------------------------------------------------- errors


class InjectedFault(Exception):
    """Base class for raise-style injected faults."""


class WorkerCrash(InjectedFault):
    """Kills the worker thread — the moral equivalent of a replica
    process dying.  Never caught by the worker loop; the thread exits
    and the fleet supervisor fails its in-flight requests over."""


class DispatchFault(InjectedFault):
    """One dispatch failed transiently; the worker loop retries the
    same step on its next iteration."""


class TransientSubmitError(RuntimeError):
    """A submit that would succeed if retried.  Subclasses RuntimeError
    so un-retried paths degrade to the gateway's existing 503 handling
    instead of a 500."""


class WorkerDeadError(RuntimeError):
    """A command was issued to a worker whose engine thread has died
    (crashed or stopped).  Typed so callers can distinguish "replica is
    gone, fail over" from a mere timeout."""


# ---------------------------------------------------------------- metrics
_SRV_FAULTS = _obs_metrics.counter(
    "serving.faults_injected",
    "faults fired by the injection layer, by site and kind")
_SRV_FAILOVERS = _obs_metrics.counter(
    "serving.failovers",
    "in-flight requests re-dispatched to a surviving replica")
_SRV_RETRIES = _obs_metrics.counter(
    "serving.retries",
    "submit attempts retried after a transient failure")
_SRV_DEGRADATION = _obs_metrics.gauge(
    "serving.degradation_level",
    "engine graceful-degradation ladder level "
    "(0 normal, 1 spec off, 2 horizon=1, 3 shedding)")
_SRV_SHED = _obs_metrics.counter(
    "serving.degradation_shed",
    "queued requests shed by the degradation ladder")

#: degradation-ladder level names, indexed by level
DEGRADE_LEVELS = ("normal", "no_spec", "horizon_1", "shed")


# ------------------------------------------------------------------- plan
@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: fire ``kind`` at ``site`` on visit ordinals
    ``at .. at+times-1`` (0-based, counted per ``(scope, site)``).
    ``scope`` names the worker/engine the fault targets; ``""`` matches
    any scope."""

    site: str
    kind: str
    at: int
    scope: str = ""
    times: int = 1

    def __post_init__(self):
        if self.site not in SITE_KINDS:
            raise ValueError(f"unknown fault site {self.site!r}; "
                             f"one of {sorted(SITE_KINDS)}")
        if self.kind not in SITE_KINDS[self.site]:
            raise ValueError(
                f"kind {self.kind!r} not valid at site {self.site!r}; "
                f"one of {SITE_KINDS[self.site]}")
        if self.at < 0 or self.times < 1:
            raise ValueError("need at >= 0 and times >= 1")

    def matches(self, scope, site, ordinal):
        return (self.site == site
                and self.scope in ("", scope)
                and self.at <= ordinal < self.at + self.times)


class FaultPlan:
    """An immutable schedule of :class:`FaultSpec`\\ s.

    The plan is pure data — it never counts anything; pair it with a
    :class:`FaultInjector` (which owns the ordinal counters) to arm it.
    One plan can arm many injectors: each replays identically."""

    def __init__(self, specs=(), seed=0):
        self.specs = tuple(specs)
        self.seed = int(seed)

    def match(self, scope, site, ordinal):
        """First spec firing at this (scope, site, ordinal), or None."""
        for spec in self.specs:
            if spec.matches(scope, site, ordinal):
                return spec
        return None

    @classmethod
    def chaos(cls, seed, scopes, n_faults=6, max_ordinal=24,
              kinds=(FAULT_CRASH, FAULT_STALL, FAULT_EXCEPTION,
                     FAULT_SUBMIT_FAIL, FAULT_POOL_EXHAUSTED)):
        """Derive a whole chaos schedule from one seed: ``n_faults``
        faults of the given kinds spread over the given scopes at
        ordinals in ``[0, max_ordinal)``.  At most one *fatal* fault
        (crash/stall) per scope — a chaos run that kills every replica
        proves nothing about recovery."""
        rng = random.Random(int(seed))
        site_of = {k: s for s, ks in SITE_KINDS.items() for k in ks}
        specs, used, fatal_scopes = [], set(), set()
        attempts = 0
        while len(specs) < int(n_faults) and attempts < 200:
            attempts += 1
            kind = rng.choice(list(kinds))
            scope = rng.choice(list(scopes))
            ordinal = rng.randrange(int(max_ordinal))
            fatal = kind in (FAULT_CRASH, FAULT_STALL)
            if fatal and scope in fatal_scopes:
                continue
            key = (scope, site_of[kind], ordinal)
            if key in used:
                continue
            used.add(key)
            if fatal:
                fatal_scopes.add(scope)
            specs.append(FaultSpec(site_of[kind], kind, ordinal,
                                   scope=scope))
        specs.sort(key=lambda s: (s.scope, s.site, s.at, s.kind))
        return cls(specs, seed=seed)

    def to_json(self):
        return {"seed": self.seed,
                "specs": [vars(s).copy() if not hasattr(s, "__dict__")
                          else dict(site=s.site, kind=s.kind, at=s.at,
                                    scope=s.scope, times=s.times)
                          for s in self.specs]}

    def __repr__(self):
        return f"FaultPlan(seed={self.seed}, specs={list(self.specs)})"


class FaultInjector:
    """Arms a :class:`FaultPlan`: owns the per-``(scope, site)`` visit
    counters and fires matching faults.  Thread-safe — every worker
    thread of a fleet can share one injector (per-scope ordinals keep
    their schedules independent).

    ``fire(site, scope)`` advances the ordinal and, on a match, either
    raises (crash/exception/submit_fail) or returns the spec
    (stall/pool_exhausted — behaviours the *caller* must act out;
    raising "stall" would be a lie).  No match returns None.  Every
    fired fault is appended to :attr:`fired` — the replay record a
    chaos test reconciles against."""

    def __init__(self, plan):
        if isinstance(plan, (list, tuple)):
            plan = FaultPlan(plan)
        self.plan = plan
        self._lock = threading.Lock()
        self._ordinals = {}            # (scope, site) -> visits so far
        self.fired = []                # (scope, site, kind, ordinal)

    def fire(self, site, scope=""):
        with self._lock:
            n = self._ordinals.get((scope, site), 0)
            self._ordinals[(scope, site)] = n + 1
            spec = self.plan.match(scope, site, n)
            if spec is None:
                return None
            self.fired.append((scope, site, spec.kind, n))
        _SRV_FAULTS.inc(site=site, kind=spec.kind)
        _obs_events.instant("serving.fault_injected", cat="serving",
                            site=site, kind=spec.kind, scope=scope,
                            ordinal=n)
        if spec.kind == FAULT_CRASH:
            raise WorkerCrash(
                f"injected crash at {scope or '?'}:{site} ordinal {n}")
        if spec.kind == FAULT_EXCEPTION:
            raise DispatchFault(
                f"injected dispatch fault at {scope or '?'}:{site} "
                f"ordinal {n}")
        if spec.kind == FAULT_SUBMIT_FAIL:
            raise TransientSubmitError(
                f"injected transient submit failure at "
                f"{scope or '?'}:{site} ordinal {n}")
        return spec                    # stall / pool_exhausted

    def counts(self):
        """Fired-fault totals by kind (the reconciliation view)."""
        out = {}
        with self._lock:
            for _, _, kind, _ in self.fired:
                out[kind] = out.get(kind, 0) + 1
        return out


# ------------------------------------------------------------------ retry
def _jitter_fraction(seed, ordinal, attempt):
    """Deterministic jitter in [0, 1): a pure blake2b hash of
    (seed, ordinal, attempt) — two gateways with the same seed retry
    with the same delays, and a replayed chaos run sleeps identically."""
    h = hashlib.blake2b(f"{seed}|{ordinal}|{attempt}".encode(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big") / 2.0 ** 64


@dataclass
class RetryPolicy:
    """Capped exponential backoff with deterministic jitter.

    ``delay(ordinal, attempt)`` is the sleep before retry ``attempt``
    (0-based) of request ``ordinal``: ``min(cap, base * 2**attempt)``
    scaled into ``[0.5, 1.0)`` of itself by the jitter hash — full
    determinism, yet no two requests' retries synchronize into a
    thundering herd.  ``max_retries`` is the per-request budget; only
    after it is spent may the caller surface a 503, with
    ``delay(ordinal, attempt+1)`` as the honest Retry-After."""

    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 1.0
    seed: int = 0

    def delay(self, ordinal, attempt):
        base = min(float(self.backoff_cap_s),
                   float(self.backoff_base_s) * (2.0 ** int(attempt)))
        return base * (0.5 + 0.5 * _jitter_fraction(self.seed, ordinal,
                                                    attempt))
