"""Host-RAM spill arena under the unified paged KV pool: the tier that
turns eviction and preemption from "recompute it" into "copy it back".

Today's device pool is a strict cache of computed KV: an LRU-evicted
radix chain is simply gone, and a preempted lane re-prefills its whole
history at O(context) FLOPs.  This module adds the tier below it — a
numpy-backed, byte-budgeted host arena holding ``device_get`` copies of

* **demoted prefix blocks** — ``PrefixCache._evict`` hands the victim's
  full token path and block bytes here instead of dropping them, so the
  effective prefix cache stretches from HBM into host RAM; and
* **preempted lane images** — ``Engine.preempt`` saves the lane's whole
  block chain keyed by request id, so re-admission can re-bind the
  blocks with one batched host→device upload instead of re-prefilling.

Bitwise safety is inherited, not re-proven: stored bytes are exactly
the bytes the device pool held.  For fp pools a block's bytes are a
pure function of the tokens it covers (prefill-vs-decode write parity,
the preemption-resume doctrine engine.py already enforces); for int8
pools the per-token write-once absmax scales (``paged_write_quant``)
make stored bytes a pure function of each token's k/v vector — so a
host round-trip is indistinguishable from recompute, and the engine's
existing resume-divergence check doubles as the parity gate.  int8
blocks are stored at their quantized density: the arena's payload
arrays take the pool's ``store_dtype`` and the f32 scale planes ride
beside them (~4x more contexts per host byte than an fp arena).

Layout: ``k``/``v`` are ``[capacity, num_layers, block_size, kv_heads,
head_dim]`` arrays at the pool's storage dtype, plus
``[capacity, num_layers, block_size]`` f32 scale planes when the pool
is quantized — one host block mirrors one device block across every
layer, so a swap moves whole-block rows with no reshapes.  ``capacity``
is ``budget_bytes // bytes_per_block`` with ``bytes_per_block`` taken
from the DEVICE pool, so the budget means the same thing on both tiers.

Retention policy: host blocks are refcounted like device blocks.
Prefix entries are LRU-evictable (a demoted block may be dropped again
when the arena fills — that is the old behavior, now explicit in the
``serving.prefix_evictions{dest}`` split) — EXCEPT while pinned via
:meth:`pin_prefix`: the engine pins a matched run for the window
between ``match_prefix`` and ``pop_prefix``, because securing device
blocks for the swap-in can itself demote NEW victims into this arena,
and making room for those must not eat the entries about to be
promoted.  Lane images are pinned outright until consumed by a
swap-in, invalidated (abort/retire), or cleared —
a preempted request's state is never silently sacrificed to cache
pressure; instead ``save_lane`` evicts prefix entries to make room and
fails cleanly (engine falls back to recompute) when even that is not
enough.

Thread ownership (PTA510 doctrine): the arena is engine-owned state,
mutated only from the thread that drives the engine — the same
ownership rule as ``Engine.pool``/``Engine.prefix``.  It therefore
takes no locks, spawns no threads, and never blocks; cross-thread
readers get the same deal as ``Engine.stats()``: call it from the
owning thread or accept a torn-but-harmless counter read.

Deliberately NOT built here (see ARCHITECTURE "Tiered KV"): cross-host
shipping of arena blocks.  The arena is process-local; the multi-host
fleet's prefix warm-up uses it as the serialization format (ROADMAP),
but the wire protocol, the per-shard local-slice arenas a multi-host
mesh needs, and transfer scheduling are out of scope.
"""

from __future__ import annotations

import numpy as np


class _LaneImage:
    """A preempted lane's full KV block chain: ``hbs`` host blocks
    covering ``n_tokens`` positions (the last block may be partial —
    its trailing bytes are garbage the resume path never reads)."""

    __slots__ = ("hbs", "n_tokens")

    def __init__(self, hbs, n_tokens):
        self.hbs = list(hbs)
        self.n_tokens = int(n_tokens)


class _PrefixEntry:
    """One demoted radix block: ``hb`` holds the KV for the LAST
    ``block_size`` tokens of ``path`` (the full token path from the
    radix root, which is also the dict key it is indexed under).
    ``pinned`` counts in-flight swap-ins shielding it from arena-level
    LRU eviction (see :meth:`HostKVTier.pin_prefix`)."""

    __slots__ = ("hb", "path", "last_used", "pinned")

    def __init__(self, hb, path, clock):
        self.hb = hb
        self.path = path
        self.last_used = clock
        self.pinned = 0


class HostKVTier:
    """The pinned host arena: refcounted block index over preallocated
    numpy payload arrays, with a prefix index (token path -> entry,
    LRU-evictable) and a lane-image index (request id -> pinned chain).

    All payload setters/getters move raw block bytes; nothing here
    knows about tokens' meaning, sampling, or sharding — the engine
    owns which device blocks map to which host blocks and when.
    """

    def __init__(self, num_layers, block_size, kv_heads, head_dim,
                 store_dtype, budget_bytes, bytes_per_block,
                 quantized=False):
        self.num_layers = int(num_layers)
        self.block_size = int(block_size)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.store_dtype = np.dtype(store_dtype)
        self.quantized = bool(quantized)
        self.bytes_per_block = int(bytes_per_block)
        self.budget_bytes = int(budget_bytes)
        self.capacity = max(0, self.budget_bytes // self.bytes_per_block)
        shape = (self.capacity, self.num_layers, self.block_size,
                 self.kv_heads, self.head_dim)
        self.k = np.zeros(shape, self.store_dtype)
        self.v = np.zeros(shape, self.store_dtype)
        if self.quantized:
            sshape = (self.capacity, self.num_layers, self.block_size)
            self.k_scale = np.zeros(sshape, np.float32)
            self.v_scale = np.zeros(sshape, np.float32)
        else:
            self.k_scale = self.v_scale = None
        self._refs = np.zeros(self.capacity, np.int32)
        self._free = list(range(self.capacity - 1, -1, -1))
        self._prefix = {}            # token path tuple -> _PrefixEntry
        self._lanes = {}             # request_id -> _LaneImage
        self._clock = 0
        # counters (engine surfaces them through stats()["kv_pool"])
        self.demotions = 0           # prefix blocks accepted from _evict
        self.demotions_dropped = 0   # spills refused (arena full)
        self.promotions = 0          # prefix blocks swapped back in
        self.lane_saves = 0
        self.lane_restores = 0
        self.lane_drops = 0          # images invalidated unconsumed
        self.prefix_evictions = 0    # arena-level LRU drops

    # ------------------------------------------------------ block index
    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def blocks_in_use(self):
        return self.capacity - len(self._free)

    @property
    def bytes_in_use(self):
        return self.blocks_in_use * self.bytes_per_block

    @property
    def occupancy(self):
        return (self.blocks_in_use / self.capacity
                if self.capacity else 0.0)

    def _alloc(self):
        """Claim a free host block (refcount 1), evicting LRU prefix
        entries if the free list is dry; None when even that fails
        (everything left is pinned lane images)."""
        if not self._free and not self._evict_lru_prefix():
            return None
        hb = self._free.pop()
        self._refs[hb] = 1
        return hb

    def release(self, hb):
        """Drop one reference; the block returns to the free list when
        the last holder lets go."""
        if self._refs[hb] <= 0:
            raise ValueError(f"host block {hb} over-released")
        self._refs[hb] -= 1
        if self._refs[hb] == 0:
            self._free.append(hb)

    def _evict_lru_prefix(self):
        """Drop the least-recently-used unpinned prefix entry (lane
        images are pinned outright and entries under a
        :meth:`pin_prefix` hold are skipped — neither is ever a
        victim).  Returns True if one was freed."""
        victim = min((e for e in self._prefix.values() if not e.pinned),
                     key=lambda e: e.last_used, default=None)
        if victim is None:
            return False
        del self._prefix[victim.path]
        self.release(victim.hb)
        self.prefix_evictions += 1
        return True

    def _write_block(self, hb, kd, vd, ksd=None, vsd=None):
        self.k[hb] = kd
        self.v[hb] = vd
        if self.quantized:
            self.k_scale[hb] = ksd
            self.v_scale[hb] = vsd

    def read_block(self, hb):
        """(k, v, k_scale, v_scale) views of one host block — the
        engine stacks these into its batched upload.  Scale planes are
        None on fp arenas."""
        if self.quantized:
            return (self.k[hb], self.v[hb],
                    self.k_scale[hb], self.v_scale[hb])
        return self.k[hb], self.v[hb], None, None

    # ---------------------------------------------------- prefix spills
    def store_prefix(self, path, kd, vd, ksd=None, vsd=None):
        """Accept one demoted radix block: ``path`` is the FULL token
        path from the radix root through this block (the re-match key),
        ``kd``/``vd`` the ``[num_layers, block_size, kv_heads,
        head_dim]`` device_get payloads.  Returns True when stored;
        False (counted ``demotions_dropped``) when the arena cannot
        make room — the old drop-on-evict behavior."""
        path = tuple(path)
        self._clock += 1
        old = self._prefix.get(path)
        if old is not None:
            # re-demotion of a path we already hold: refresh in place
            self._write_block(old.hb, kd, vd, ksd, vsd)
            old.last_used = self._clock
            self.demotions += 1
            return True
        hb = self._alloc()
        if hb is None:
            self.demotions_dropped += 1
            return False
        self._write_block(hb, kd, vd, ksd, vsd)
        self._prefix[path] = _PrefixEntry(hb, path, self._clock)
        self.demotions += 1
        return True

    def match_prefix(self, tokens, start_block):
        """The longest run of consecutive demoted FULL blocks extending
        a device-side radix match: block indices ``start_block,
        start_block+1, ...`` of ``tokens`` whose full token paths are
        all held here.  Pure lookup — but NOT a reservation: a new
        spill landing before :meth:`pop_prefix` can LRU-evict a matched
        entry; callers that do work between match and pop (the engine
        allocates device blocks, whose reclaim path spills) must
        :meth:`pin_prefix` the result for that window.  A block
        covering tokens up
        to exactly ``len(tokens)`` is still promotable: the radix
        store's one-token-to-prefill invariant lives in its MATCH caps
        (``acquire``/``lookup`` stop at ``len - 1``, partially serving
        the last node copy-on-write), not in which nodes exist."""
        bs = self.block_size
        out = []
        i = int(start_block)
        while (i + 1) * bs <= len(tokens):
            path = tuple(tokens[:(i + 1) * bs])
            if path not in self._prefix:
                break
            out.append(path)
            i += 1
        return out

    def pin_prefix(self, paths):
        """Shield matched entries from arena-level LRU eviction for the
        match->pop window of a swap-in: while the engine secures device
        blocks, its reclaim fallback can demote NEW radix victims into
        this arena, and ``store_prefix`` making room for them must not
        eat the entries about to be promoted.  Pins nest (a counter per
        entry); paths already gone are ignored — ``pop_prefix`` reports
        the miss.  Pair every call with :meth:`unpin_prefix`."""
        for p in paths:
            entry = self._prefix.get(tuple(p))
            if entry is not None:
                entry.pinned += 1

    def unpin_prefix(self, paths):
        """Release a :meth:`pin_prefix` hold.  Safe on paths since
        consumed by ``pop_prefix`` (the pop already removed them)."""
        for p in paths:
            entry = self._prefix.get(tuple(p))
            if entry is not None and entry.pinned > 0:
                entry.pinned -= 1

    def pop_prefix(self, path):
        """Consume one matched entry for promotion: removes it from the
        index and returns its host block id — or None when the entry is
        gone, so an unpinned caller degrades to recompute instead of
        crashing (arena-level LRU eviction CAN invalidate
        ``match_prefix`` results; see its docstring).  The caller reads
        the payload (``read_block``), uploads it, then ``release``s the
        block."""
        entry = self._prefix.pop(tuple(path), None)
        if entry is None:
            return None
        self._clock += 1
        self.promotions += 1
        return entry.hb

    # ------------------------------------------------------ lane images
    def save_lane(self, request_id, n_tokens, blocks):
        """Store a preempted lane's full chain: ``blocks`` is a list of
        ``(kd, vd, ksd, vsd)`` per-block payloads in chain order,
        covering ``n_tokens`` positions.  All-or-nothing: if the arena
        cannot hold the whole chain even after evicting every prefix
        entry, nothing is kept and False is returned (the engine falls
        back to recompute-on-resume).  A previous unconsumed image for
        the same request is replaced."""
        self.drop_lane(request_id)
        hbs = []
        for kd, vd, ksd, vsd in blocks:
            hb = self._alloc()
            if hb is None:
                for h in hbs:
                    self.release(h)
                return False
            self._write_block(hb, kd, vd, ksd, vsd)
            hbs.append(hb)
        self._lanes[request_id] = _LaneImage(hbs, n_tokens)
        self.lane_saves += 1
        return True

    def peek_lane(self, request_id):
        """The saved image for a request, or None (non-consuming)."""
        return self._lanes.get(request_id)

    def take_lane(self, request_id):
        """Consume a lane image for swap-in: removes it from the index
        and returns it.  The caller uploads the blocks it needs and
        ``release``s every host block of the image (used or not)."""
        img = self._lanes.pop(request_id, None)
        if img is not None:
            self.lane_restores += 1
        return img

    def drop_lane(self, request_id):
        """Invalidate an unconsumed image (abort/retire/re-save): its
        blocks return to the free list.  Idempotent."""
        img = self._lanes.pop(request_id, None)
        if img is None:
            return False
        for hb in img.hbs:
            self.release(hb)
        self.lane_drops += 1
        return True

    # ------------------------------------------------------------ admin
    def clear_prefixes(self):
        """Drop every demoted prefix entry (drain: cache content is
        disposable; anything still held afterwards is a leaked lane
        image).  Returns how many entries were dropped."""
        n = len(self._prefix)
        for entry in list(self._prefix.values()):
            del self._prefix[entry.path]
            self.release(entry.hb)
        return n

    def clear(self):
        """Drop everything — prefix entries AND lane images."""
        self.clear_prefixes()
        for rid in list(self._lanes):
            self.drop_lane(rid)

    # ------------------------------------------------------------ stats
    def stats(self):
        return {
            "capacity_blocks": self.capacity,
            "free_blocks": self.free_blocks,
            "blocks_in_use": self.blocks_in_use,
            "bytes_in_use": self.bytes_in_use,
            "budget_bytes": self.budget_bytes,
            "bytes_per_block": self.bytes_per_block,
            "occupancy": self.occupancy,
            "prefix_entries": len(self._prefix),
            "lane_images": len(self._lanes),
            "demotions": self.demotions,
            "demotions_dropped": self.demotions_dropped,
            "promotions": self.promotions,
            "lane_saves": self.lane_saves,
            "lane_restores": self.lane_restores,
            "lane_drops": self.lane_drops,
            "prefix_evictions": self.prefix_evictions,
            "store_dtype": str(self.store_dtype),
            "quantized": self.quantized,
        }
