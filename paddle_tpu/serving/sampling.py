"""Token sampling for the serving engine.

Greedy / temperature / top-k / top-p under a per-request seeded PRNG.
Everything is expressed as pure jnp on a single logits row so the engine
can ``vmap`` it across slots inside the fused decode step: a request's
k-th sampled token depends only on (its seed, k, its logits) — never on
which slot it occupies or what else is in the batch.  That independence
is what makes continuous batching reproduce sequential ``generate()``
token-for-token.

The same property makes horizon-scanned decode exact: the engine keeps
a per-slot sample counter in the scan carry and derives each step's key
as ``request_key(seed, counter)`` — i.e. ``fold_in(seed, n_generated)``
— so whether H tokens come from one fused ``lax.scan`` dispatch or H
separate step dispatches, token k of a request is sampled with the
identical key and is bitwise-equal across horizons.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

#: logit floor for grammar-masked (disallowed) tokens: finite (so the
#: temperature divide and softmax stay NaN-free at any temperature) but
#: far below every real logit, so neither argmax nor categorical can
#: pick a masked token.  Matches the established masking floor used by
#: the attention kernels.
MASK_FLOOR = -1.0e30


@dataclass
class SamplingParams:
    """Per-request decoding controls (paddle parity: the generate()
    kwargs of PaddleNLP's GenerationMixin, reduced to the serving set).

    temperature <= 0 selects greedy argmax decoding; top_k <= 0 and
    top_p >= 1.0 disable their respective filters.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    max_new_tokens: int = 16
    eos_token_id: int | None = None
    seed: int = 0

    def validate(self):
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        return self


def request_key(seed, n_sampled):
    """The PRNG key for a request's n_sampled-th token: a pure function
    of (seed, token index), so replays and re-batchings are bitwise
    deterministic."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), n_sampled)


def sample_token(logits, key, temperature, top_k, top_p):
    """Sample one token id from a single [vocab] logits row.

    All four controls are traced values, so one compiled program serves
    every request mix.  Greedy rows still draw nothing from ``key`` —
    the argmax branch is selected by ``where``.
    """
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # temperature scale (guard the greedy rows against divide-by-zero)
    t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits.astype(jnp.float32) / t

    # top-k: keep logits >= the k-th largest (ties widen the pool)
    sorted_desc = jnp.sort(scaled)[::-1]
    k_idx = jnp.clip(top_k, 1, vocab) - 1
    kth = jnp.take(sorted_desc, k_idx)
    scaled = jnp.where((top_k > 0) & (scaled < kth), -jnp.inf, scaled)

    # top-p (nucleus): keep the smallest prefix of the sorted
    # distribution whose mass reaches top_p
    probs = jax.nn.softmax(scaled)
    sp = jnp.sort(probs)[::-1]
    cum = jnp.cumsum(sp)
    cutoff_idx = jnp.argmax(cum >= top_p)          # first index reaching p
    threshold = jnp.take(sp, cutoff_idx)
    scaled = jnp.where((top_p < 1.0) & (probs < threshold), -jnp.inf,
                       scaled)

    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def sample_batch(logits, seeds, counts, temperatures, top_ks, top_ps):
    """Vectorized sampling across slot rows: logits [N, vocab] plus
    per-slot parameter arrays [N] -> token ids [N] int32.

    When EVERY row is greedy (temperature <= 0) the whole
    sort/filter/categorical pipeline is provably dead — each row
    reduces to ``argmax`` — so a runtime ``lax.cond`` skips it.  The
    branch predicate is data-dependent, not traced shape, so one
    compiled program still serves every request mix; the greedy branch
    returns exactly what the full pipeline's ``where(temperature > 0,
    ...)`` would have picked, so outputs are bitwise unchanged."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def full(_):
        keys = jax.vmap(request_key)(seeds, counts)
        return jax.vmap(sample_token)(logits, keys, temperatures,
                                      top_ks, top_ps)

    return jax.lax.cond(jnp.any(temperatures > 0), full,
                        lambda _: greedy, None)


def sample_window(logits, seeds, counts, temperatures, top_ks, top_ps,
                  allowed=None):
    """Sampling across a speculative verify window: logits [N, W, vocab]
    -> token ids [N, W], where window position j of lane i is sampled
    with key ``request_key(seeds[i], counts[i] + j)`` — the exact key
    sequential decode would use for that request's (counts+j)-th token.
    Keys are pure functions of (seed, index), so the verify forward
    consumes no PRNG state for positions the acceptance rule discards:
    emitted token k of a request is bitwise the token sequential
    ``generate()`` samples, whatever W the engine verified with.

    ``allowed`` (optional, [N, W, vocab] bool) is the grammar mask:
    disallowed logits drop to ``MASK_FLOOR`` BEFORE the all-greedy fast
    path / categorical pipeline, so constrained sampling inherits the
    same key discipline and stays bitwise-reproducible; an all-True row
    (the accept-all sentinel state unconstrained lanes ride) is the
    identity — ``where(True, x, floor)`` is bitwise ``x``."""
    n, w, vocab = logits.shape
    if allowed is not None:
        logits = jnp.where(allowed, logits, MASK_FLOOR)
    js = jnp.arange(w, dtype=counts.dtype)
    rep = lambda a: jnp.repeat(a, w, axis=0)
    flat_counts = (counts[:, None] + js[None, :]).reshape(-1)
    out = sample_batch(logits.reshape(n * w, vocab), rep(seeds),
                       flat_counts, rep(temperatures), rep(top_ks),
                       rep(top_ps))
    return out.reshape(n, w)
