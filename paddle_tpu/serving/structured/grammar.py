"""Grammar-constrained decoding, layer 1: the grammar compiler.

Compiles a regex (or a practical JSON-schema subset lowered to regex)
into a token-level DFA over the model vocabulary:

    regex --> Thompson NFA (interval-labeled transitions)
          --> subset construction (alphabet-partitioned char DFA)
          --> Moore minimization + co-accessibility pruning
          --> vocab crossproduct: walk every vocab token string through
              the char DFA from every state

The crossproduct emits two device-ready arrays per grammar:

  * a dense ``[num_states, vocab]`` int32 transition table mapping
    (state, token) -> next state, ``REJECT`` (-1) where the token is
    illegal — the *advance* structure;
  * a packed ``[num_states, ceil(vocab/32)]`` uint32 allowed-token
    bitmask — the *mask* structure consumed by ``sample_window``.

The two are views of one relation (``table[s, t] >= 0`` iff mask bit
``t`` of row ``s`` is set); the engine advances states with the dense
table and masks logits with the bitmask, and a unit test pins the
equivalence.

EOS is the grammar's stop contract: the EOS column is legal exactly in
accepting states (where it self-loops — the lane retires on EOS before
the state matters again), so a constrained lane can stop if and only if
its emitted text is a complete sentence of the grammar.  Together with
co-accessibility pruning (every surviving state reaches an accepting
state) and a vocab-reachability check (every token-reachable state
keeps at least one legal token), a constrained lane can never strand:
there is always a legal token, and following legal tokens never reaches
``REJECT``.

``GrammarSlab`` is the host master for the fixed-capacity device slab
the engine uploads: row 0 is the reserved accept-all sentinel that
unconstrained lanes ride (all tokens legal, self-loop), and compiled
grammars install at refcounted offsets >= 1 so grammars of any size
share one device allocation and one compiled program.  The slab is
single-owner: only the engine thread that owns the Engine mutates it
(the PTA51x thread-ownership rule the analysis gate lints).
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

__all__ = [
    "GrammarError",
    "GrammarSpec",
    "as_grammar_spec",
    "CharDFA",
    "TokenDFA",
    "REJECT",
    "compile_regex",
    "compile_grammar",
    "schema_to_regex",
    "GrammarSlab",
]

#: next-state value for an illegal (state, token) pair in TokenDFA.
REJECT = -1

_MAXCP = 0x10FFFF
#: repetition bounds above this expand the NFA quadratically; refuse.
_MAX_REPEAT = 256
#: JSON-schema lowering recursion cap (bounded nesting by contract).
_MAX_SCHEMA_DEPTH = 16


class GrammarError(ValueError):
    """A grammar the compiler does not accept.

    Raised eagerly at validation/compile time with the unsupported
    construct named in the message — the gateway maps it to a 400
    ``invalid_grammar`` typed error, mirroring ``SamplingParams``
    validation style.
    """


# ---------------------------------------------------------------------------
# character sets: sorted disjoint inclusive codepoint intervals
# ---------------------------------------------------------------------------


def _normalize(ranges):
    rs = sorted((lo, hi) for lo, hi in ranges if lo <= hi)
    out = []
    for lo, hi in rs:
        if out and lo <= out[-1][1] + 1:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return tuple(out)


def _negate(ranges):
    out, cur = [], 0
    for lo, hi in _normalize(ranges):
        if lo > cur:
            out.append((cur, lo - 1))
        cur = hi + 1
    if cur <= _MAXCP:
        out.append((cur, _MAXCP))
    return tuple(out)


_DIGIT = ((48, 57),)
_WORD = _normalize([(48, 57), (65, 90), (95, 95), (97, 122)])
_SPACE = _normalize([(9, 13), (32, 32)])
_DOT = _negate([(10, 10)])  # any char but newline

_ESCAPE_SETS = {
    "d": _DIGIT, "D": _negate(_DIGIT),
    "w": _WORD, "W": _negate(_WORD),
    "s": _SPACE, "S": _negate(_SPACE),
}
_ESCAPE_CHARS = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "v": "\v",
                 "0": "\0"}
_HEXDIGITS = frozenset("0123456789abcdefABCDEF")


# ---------------------------------------------------------------------------
# regex parser -> AST
#   ("set", ranges) | ("cat", parts) | ("alt", branches)
#   ("rep", node, min, max_or_None) | ("eps",)
# ---------------------------------------------------------------------------


class _RegexParser:
    def __init__(self, pattern):
        self.p = pattern
        self.i = 0

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else ""

    def _take(self):
        c = self._peek()
        if not c:
            raise GrammarError("regex: unexpected end of pattern")
        self.i += 1
        return c

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise GrammarError(
                f"regex: unexpected {self.p[self.i]!r} at index {self.i}"
                " (unbalanced ')'?)")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self._peek() == "|":
            self.i += 1
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else \
            ("alt", tuple(branches))

    def _cat(self):
        parts = []
        while self._peek() not in ("", "|", ")"):
            parts.append(self._repeat())
        if not parts:
            return ("eps",)
        return parts[0] if len(parts) == 1 else ("cat", tuple(parts))

    def _repeat(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self.i += 1
                node = ("rep", node, 0, None)
            elif c == "+":
                self.i += 1
                node = ("rep", node, 1, None)
            elif c == "?":
                self.i += 1
                node = ("rep", node, 0, 1)
            elif c == "{":
                node = ("rep", node, *self._bounds())
            else:
                return node

    def _bounds(self):
        j = self.p.find("}", self.i)
        if j < 0:
            raise GrammarError("regex: unescaped '{' (use \\{ for a "
                               "literal brace)")
        body = self.p[self.i + 1:j]
        self.i = j + 1
        lo, _, hi = body.partition(",")
        try:
            m = int(lo)
            mx = m if "," not in body else (int(hi) if hi else None)
        except ValueError:
            raise GrammarError(
                f"regex: malformed repetition bound {{{body}}}") from None
        if m < 0 or (mx is not None and mx < m):
            raise GrammarError(
                f"regex: invalid repetition bound {{{body}}}")
        if m > _MAX_REPEAT or (mx or 0) > _MAX_REPEAT:
            raise GrammarError(
                f"regex: repetition bound {{{body}}} exceeds the "
                f"{_MAX_REPEAT} expansion cap")
        return m, mx

    def _atom(self):
        c = self._take()
        if c == "(":
            if self.p[self.i:self.i + 2] == "?:":
                self.i += 2
            elif self._peek() == "?":
                raise GrammarError(
                    "regex: (?...) groups (lookaround, flags, named "
                    "groups) are not supported; only (?:...) and (...)")
            node = self._alt()
            if self._peek() != ")":
                raise GrammarError("regex: unbalanced '('")
            self.i += 1
            return node
        if c == "[":
            return ("set", self._cls())
        if c == ".":
            return ("set", _DOT)
        if c == "\\":
            return self._escape()
        if c in "*+?{":
            raise GrammarError(f"regex: nothing to repeat before {c!r}")
        if c in "^$":
            raise GrammarError(
                f"regex: anchors ({c!r}) are not supported — the "
                "compiled DFA is full-match by construction")
        return ("set", ((ord(c), ord(c)),))

    def _escape(self):
        c = self._take()
        if c in _ESCAPE_SETS:
            return ("set", _ESCAPE_SETS[c])
        if c in _ESCAPE_CHARS:
            o = ord(_ESCAPE_CHARS[c])
            return ("set", ((o, o),))
        if c in ("x", "u"):
            o = self._hex_escape(c)
            return ("set", ((o, o),))
        if not c.isalnum():
            return ("set", ((ord(c), ord(c)),))
        raise GrammarError(f"regex: unsupported escape \\{c}"
                           " (\\b word boundaries and backreferences "
                           "are not supported)")

    def _hex_escape(self, kind):
        """``\\xHH`` / ``\\uHHHH``: exactly 2/4 hex digits.  ``int(_,
        16)`` alone would accept a truncated escape ('a\\x4', '\\u12')
        — or '+'/'_'-decorated strings — as a shorter codepoint instead
        of raising."""
        n = 2 if kind == "x" else 4
        hexs = self.p[self.i:self.i + n]
        if len(hexs) != n or any(h not in _HEXDIGITS for h in hexs):
            raise GrammarError(
                f"regex: malformed \\{kind} escape (expected exactly "
                f"{n} hex digits, got {hexs!r})")
        self.i += n
        return int(hexs, 16)

    def _cls(self):
        negate = False
        if self._peek() == "^":
            negate = True
            self.i += 1
        ranges = []
        while True:
            c = self._take()
            if c == "]":
                break
            lo = self._cls_cp(c)
            if isinstance(lo, tuple):   # a \d/\w/\s-style set
                ranges.extend(lo)
                continue
            if self._peek() == "-" and self.p[self.i + 1:self.i + 2] \
                    not in ("]", ""):
                self.i += 1
                hi = self._cls_cp(self._take())
                if isinstance(hi, tuple):
                    raise GrammarError(
                        "regex: a character-set escape cannot end a "
                        "range")
                if hi < lo:
                    raise GrammarError(
                        f"regex: bad range {chr(lo)}-{chr(hi)}")
                ranges.append((lo, hi))
            else:
                ranges.append((lo, lo))
        if not ranges:
            raise GrammarError("regex: empty character class []")
        rs = _normalize(ranges)
        return _negate(rs) if negate else rs

    def _cls_cp(self, c):
        """One class item: a codepoint, or a ranges tuple for set
        escapes like ``\\d`` (which cannot bound a range)."""
        if c != "\\":
            return ord(c)
        e = self._take()
        if e in _ESCAPE_SETS:
            return _ESCAPE_SETS[e]
        if e in _ESCAPE_CHARS:
            return ord(_ESCAPE_CHARS[e])
        if e in ("x", "u"):
            return self._hex_escape(e)
        if e == "b":               # backspace inside a class
            return 8
        if not e.isalnum():
            return ord(e)
        raise GrammarError(f"regex: unsupported class escape \\{e}")


# ---------------------------------------------------------------------------
# Thompson NFA
# ---------------------------------------------------------------------------


class _NFA:
    def __init__(self):
        self.n = 0
        self.by_src = {}   # state -> list of (ranges, dst)
        self.eps = {}      # state -> list of dst

    def state(self):
        s = self.n
        self.n += 1
        self.by_src[s] = []
        self.eps[s] = []
        return s

    def edge(self, src, ranges, dst):
        self.by_src[src].append((ranges, dst))

    def epsilon(self, src, dst):
        self.eps[src].append(dst)


def _frag(nfa, node):
    """Thompson-construct ``node``; returns (start, end) states."""
    kind = node[0]
    if kind == "eps":
        s = nfa.state()
        return s, s
    if kind == "set":
        s, e = nfa.state(), nfa.state()
        nfa.edge(s, node[1], e)
        return s, e
    if kind == "cat":
        s, e = _frag(nfa, node[1][0])
        for part in node[1][1:]:
            ps, pe = _frag(nfa, part)
            nfa.epsilon(e, ps)
            e = pe
        return s, e
    if kind == "alt":
        s, e = nfa.state(), nfa.state()
        for br in node[1]:
            bs, be = _frag(nfa, br)
            nfa.epsilon(s, bs)
            nfa.epsilon(be, e)
        return s, e
    if kind == "rep":
        _, sub, m, mx = node
        s = e = nfa.state()
        for _i in range(m):            # mandatory copies, chained
            cs, ce = _frag(nfa, sub)
            nfa.epsilon(e, cs)
            e = ce
        if mx is None:                 # Kleene tail
            cs, ce = _frag(nfa, sub)
            tail = nfa.state()
            nfa.epsilon(e, cs)
            nfa.epsilon(e, tail)
            nfa.epsilon(ce, cs)
            nfa.epsilon(ce, tail)
            return s, tail
        end = nfa.state()
        nfa.epsilon(e, end)            # may stop after the m copies
        for _i in range(mx - m):       # optional copies, each may bail
            cs, ce = _frag(nfa, sub)
            nfa.epsilon(e, cs)
            e = ce
            nfa.epsilon(e, end)
        return s, end
    raise AssertionError(f"unknown AST node {kind}")


# ---------------------------------------------------------------------------
# char-level DFA: subset construction, minimization, pruning
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CharDFA:
    """Minimized, co-accessible character DFA.  State 0 is the start;
    missing transitions are implicit rejection."""

    accepting: frozenset
    trans: tuple    # trans[state] = tuple of (lo, hi, dst), sorted

    @property
    def n_states(self):
        return len(self.trans)

    def step(self, state, cp):
        """Next state for codepoint ``cp``, or ``REJECT``."""
        if state < 0:
            return REJECT
        for lo, hi, dst in self.trans[state]:
            if lo <= cp <= hi:
                return dst
        return REJECT

    def walk(self, state, text):
        for ch in text:
            state = self.step(state, ord(ch))
            if state < 0:
                return REJECT
        return state

    def matches(self, text):
        return self.walk(0, text) in self.accepting


def _closure(nfa, states):
    seen = set(states)
    stack = list(states)
    while stack:
        for t in nfa.eps[stack.pop()]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


def _subset(nfa, start, accept):
    start_c = _closure(nfa, {start})
    ids = {start_c: 0}
    order = [start_c]
    trans = {}
    queue = [start_c]
    while queue:
        cur = queue.pop()
        sid = ids[cur]
        edges = [(lo, hi, dst) for src in cur
                 for ranges, dst in nfa.by_src[src]
                 for lo, hi in ranges]
        bounds = sorted({lo for lo, _, _ in edges}
                        | {hi + 1 for _, hi, _ in edges})
        out = []
        for a, b1 in zip(bounds, bounds[1:]):
            tgt = frozenset(d for lo, hi, d in edges if lo <= a <= hi)
            if not tgt:
                continue
            clo = _closure(nfa, tgt)
            if clo not in ids:
                ids[clo] = len(order)
                order.append(clo)
                queue.append(clo)
            out.append((a, b1 - 1, ids[clo]))
        trans[sid] = _merge_runs(sorted(out))
    accepting = {ids[s] for s in order if accept in s}
    return len(order), trans, accepting


def _merge_runs(runs):
    out = []
    for lo, hi, dst in runs:
        if out and out[-1][2] == dst and out[-1][1] + 1 == lo:
            out[-1] = (out[-1][0], hi, dst)
        else:
            out.append((lo, hi, dst))
    return tuple(tuple(r) for r in out)


def _step_runs(runs, cp):
    for lo, hi, dst in runs:
        if lo <= cp <= hi:
            return dst
    return REJECT


def _minimize(n, trans, accepting):
    # global alphabet partition: every state's intervals are unions of
    # these atomic pieces, so one representative codepoint per piece
    # decides equivalence exactly
    bounds = sorted({lo for st in range(n) for lo, _, _ in trans[st]}
                    | {hi + 1 for st in range(n)
                       for _, hi, _ in trans[st]})
    reps = bounds[:-1] if len(bounds) > 1 else []
    part = [1 if s in accepting else 0 for s in range(n)]
    while True:
        sigs = {}
        new = [0] * n
        for s in range(n):
            sig = (part[s], tuple(
                part[d] if (d := _step_runs(trans[s], r)) >= 0 else -1
                for r in reps))
            new[s] = sigs.setdefault(sig, len(sigs))
        if len(sigs) == len(set(part)):
            break
        part = new
    # relabel blocks to contiguous 0..blocks-1: if the loop broke on
    # the first pass (e.g. every state accepting: "(a*)*", "()"), part
    # still holds its seed labels {1}, which are not 0-based
    remap = {}
    part = [remap.setdefault(b, len(remap)) for b in part]
    blocks = len(set(part))
    btrans = {}
    for s in range(n):
        b = part[s]
        if b not in btrans:
            btrans[b] = _merge_runs(
                [(lo, hi, part[d]) for lo, hi, d in trans[s]])
    baccept = {part[s] for s in accepting}
    return blocks, btrans, baccept, part[0]


def _prune_and_renumber(n, trans, accepting, start):
    fwd = {s: {d for _, _, d in trans[s]} for s in range(n)}
    reach = {start}
    stack = [start]
    while stack:
        for d in fwd[stack.pop()]:
            if d not in reach:
                reach.add(d)
                stack.append(d)
    rev = {s: set() for s in range(n)}
    for s in range(n):
        for d in fwd[s]:
            rev[d].add(s)
    coacc = set(a for a in accepting)
    stack = list(coacc)
    while stack:
        for p in rev[stack.pop()]:
            if p not in coacc:
                coacc.add(p)
                stack.append(p)
    keep = reach & coacc
    if start not in keep:
        raise GrammarError("grammar matches no string (empty language)")
    order = [start]  # BFS renumber, start first -> state 0
    ids = {start: 0}
    qi = 0
    while qi < len(order):
        s = order[qi]
        qi += 1
        for _, _, d in trans[s]:
            if d in keep and d not in ids:
                ids[d] = len(order)
                order.append(d)
    new_trans = tuple(
        _merge_runs([(lo, hi, ids[d]) for lo, hi, d in trans[s]
                     if d in keep])
        for s in order)
    new_accept = frozenset(ids[s] for s in accepting if s in keep)
    return CharDFA(accepting=new_accept, trans=new_trans)


def compile_regex(pattern):
    """Compile a regex to a minimized co-accessible :class:`CharDFA`.

    Full-match semantics (no anchors).  Raises :class:`GrammarError`
    naming the unsupported construct for anything outside the dialect:
    literals, escapes (``\\d \\w \\s`` + negations, ``\\n`` etc.,
    ``\\x``/``\\u``), classes with ranges and negation, ``|``, groups,
    ``* + ?`` and bounded ``{m}``/``{m,}``/``{m,n}``, and ``.``.
    """
    ast = _RegexParser(str(pattern)).parse()
    nfa = _NFA()
    s, e = _frag(nfa, ast)
    n, trans, accepting = _subset(nfa, s, e)
    bn, btrans, baccept, bstart = _minimize(n, trans, accepting)
    return _prune_and_renumber(bn, btrans, baccept, bstart)


# ---------------------------------------------------------------------------
# vocab crossproduct: char DFA -> token DFA
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TokenDFA:
    """Token-level DFA over the model vocabulary.  State 0 is the
    start.  ``next_state[s, t] == REJECT`` iff mask bit ``t`` of row
    ``s`` is clear — the dense table advances, the bitmask masks."""

    next_state: np.ndarray   # [S, V] int32, REJECT where illegal
    mask: np.ndarray         # [S, ceil(V/32)] uint32, bit t of word t//32
    accepting: np.ndarray    # [S] bool
    forced: np.ndarray       # [S] int32: the sole legal token, or -1
    popcount: np.ndarray     # [S] int32: number of legal tokens

    @property
    def n_states(self):
        return self.next_state.shape[0]

    @property
    def vocab_size(self):
        return self.next_state.shape[1]

    @property
    def table_bytes(self):
        return (self.next_state.nbytes + self.mask.nbytes
                + self.forced.nbytes)

    def allows(self, state, token):
        return bool((self.mask[state, token // 32]
                     >> np.uint32(token % 32)) & np.uint32(1))

    def step(self, state, token):
        return int(self.next_state[state, token])


def _pack_mask(allowed):
    """[S, V] bool -> [S, ceil(V/32)] uint32, token t = bit t%32 of
    word t//32."""
    s, v = allowed.shape
    words = (v + 31) // 32
    padded = np.zeros((s, words * 32), np.uint32)
    padded[:, :v] = allowed
    return (padded.reshape(s, words, 32)
            << np.arange(32, dtype=np.uint32)).sum(
                axis=2, dtype=np.uint32)


def compile_grammar(grammar, vocab, eos_id, vocab_size=None):
    """Compile ``grammar`` (regex string / schema dict /
    :class:`GrammarSpec`) against ``vocab`` (sequence of token strings,
    index = token id) into a :class:`TokenDFA`.

    ``eos_id`` is mandatory: EOS is legal exactly in accepting states
    (self-loop), which is how a constrained lane stops.  Tokens with
    ids >= ``len(vocab)``, empty token strings, and tokens whose walk
    rejects are illegal.  Raises :class:`GrammarError` if some
    token-reachable state would have no legal token — the vocabulary
    cannot express the grammar and a lane would strand there.
    """
    spec = as_grammar_spec(grammar)
    cdfa = compile_regex(spec.pattern)
    v = int(vocab_size if vocab_size is not None else len(vocab))
    if not 0 <= int(eos_id) < v:
        raise GrammarError(
            f"eos_id {eos_id} outside vocab of size {v}")
    s_n = cdfa.n_states
    nxt = np.full((s_n, v), REJECT, np.int32)
    for t, text in enumerate(vocab[:v]):
        if t == eos_id or not text:
            continue
        for s in range(s_n):
            d = cdfa.walk(s, text)
            if d >= 0:
                nxt[s, t] = d
    accepting = np.zeros(s_n, bool)
    accepting[list(cdfa.accepting)] = True
    nxt[accepting, int(eos_id)] = np.nonzero(accepting)[0]
    allowed = nxt >= 0
    pop = allowed.sum(axis=1).astype(np.int32)
    forced = np.where(pop == 1, allowed.argmax(axis=1), -1)
    forced = forced.astype(np.int32)
    # a lane must never strand: every state reachable by legal tokens
    # must keep at least one legal token
    seen = {0}
    stack = [0]
    while stack:
        s = stack.pop()
        if pop[s] == 0:
            raise GrammarError(
                "vocabulary cannot express this grammar: a reachable "
                "constraint state has no legal token (grammar needs a "
                "character no vocab token can begin)")
        for d in set(int(x) for x in nxt[s][allowed[s]]):
            if d not in seen:
                seen.add(d)
                stack.append(d)
    return TokenDFA(next_state=nxt, mask=_pack_mask(allowed),
                    accepting=accepting, forced=forced, popcount=pop)


# ---------------------------------------------------------------------------
# JSON-schema subset -> regex lowering
# ---------------------------------------------------------------------------

_RE_META = set(".^$*+?()[]{}|\\")

_STRING_RE = (r'"([^"\\\x00-\x1f]|\\["\\/bfnrt]'
              r'|\\u[0-9a-fA-F]{4})*"')
_INTEGER_RE = r"-?(0|[1-9][0-9]*)"
_NUMBER_RE = _INTEGER_RE + r"(\.[0-9]+)?([eE][+-]?[0-9]+)?"

_UNSUPPORTED = (
    "$ref", "$dynamicRef", "anyOf", "oneOf", "allOf", "not",
    "patternProperties", "propertyNames", "if", "then", "else",
    "dependentSchemas", "dependentRequired", "pattern", "format",
    "minLength", "maxLength", "minimum", "maximum",
    "exclusiveMinimum", "exclusiveMaximum", "multipleOf",
    "uniqueItems", "contains", "prefixItems", "additionalItems",
    "unevaluatedProperties", "minProperties", "maxProperties",
)


def _lit(text):
    """Regex-escape a literal string (e.g. a JSON-dumped enum value)."""
    return "".join("\\" + c if c in _RE_META else c for c in text)


def _json_dump(value):
    return json.dumps(value, separators=(",", ":"), sort_keys=True)


def _schema_regex(schema, depth):
    if depth > _MAX_SCHEMA_DEPTH:
        raise GrammarError(
            f"JSON schema nests deeper than the supported bound "
            f"({_MAX_SCHEMA_DEPTH})")
    if not isinstance(schema, dict):
        raise GrammarError(
            f"schema nodes must be objects, got {type(schema).__name__}")
    bad = [k for k in _UNSUPPORTED if k in schema]
    if bad:
        raise GrammarError(
            "unsupported JSON-schema feature(s): " + ", ".join(bad)
            + " (supported: type object/array/string/integer/number/"
            "boolean/null, enum, const, properties + required, items "
            "+ minItems/maxItems, additionalProperties: false)")
    if "const" in schema:
        return _lit(_json_dump(schema["const"]))
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise GrammarError("'enum' must be a non-empty list")
        return "(" + "|".join(_lit(_json_dump(v)) for v in vals) + ")"
    ty = schema.get("type")
    if isinstance(ty, list):
        return ("(" + "|".join(
            _schema_regex({**schema, "type": t}, depth + 1)
            for t in ty) + ")")
    if ty == "string":
        return _STRING_RE
    if ty == "integer":
        return _INTEGER_RE
    if ty == "number":
        return _NUMBER_RE
    if ty == "boolean":
        return "(true|false)"
    if ty == "null":
        return "null"
    if ty == "array":
        return _array_regex(schema, depth)
    if ty == "object":
        return _object_regex(schema, depth)
    raise GrammarError(
        f"unsupported or missing schema 'type': {ty!r} (supported: "
        "object, array, string, integer, number, boolean, null, or "
        "enum/const)")


def _array_regex(schema, depth):
    if "items" not in schema:
        raise GrammarError(
            "'array' schemas need 'items' (unbounded heterogeneous "
            "arrays are not supported)")
    item = _schema_regex(schema["items"], depth + 1)
    lo = int(schema.get("minItems", 0))
    hi = schema.get("maxItems")
    hi = None if hi is None else int(hi)
    if lo < 0 or (hi is not None and hi < lo):
        raise GrammarError("invalid minItems/maxItems bounds")
    if hi == 0:
        return r"\[\]"
    x = "(" + item + ")"
    if lo == 0:
        tail = "*" if hi is None else ("{0,%d}" % (hi - 1))
        body = "(" + x + "(," + x + ")" + tail + ")?"
    else:
        tail = ("{%d,}" % (lo - 1)) if hi is None else \
            ("{%d,%d}" % (lo - 1, hi - 1))
        body = x + "(," + x + ")" + tail
    return r"\[" + body + r"\]"


def _object_regex(schema, depth):
    extra = schema.get("additionalProperties", False)
    if extra is not False:
        raise GrammarError(
            "additionalProperties must be false (or omitted): "
            "free-form keys are not supported")
    props = schema.get("properties", {})
    if not isinstance(props, dict):
        raise GrammarError("'properties' must be an object")
    required = schema.get("required", [])
    unknown = [k for k in required if k not in props]
    if unknown:
        raise GrammarError(
            "required key(s) missing from 'properties': "
            + ", ".join(repr(k) for k in unknown))
    pairs = {k: _lit(json.dumps(k)) + ":"
             + _schema_regex(v, depth + 1)
             for k, v in props.items()}
    req = [k for k in props if k in set(required)]
    opt = [k for k in props if k not in set(required)]
    if req:
        # required keys in declaration order; each optional key may
        # ride behind them as an independent (,"k":V)? suffix
        body = ",".join(pairs[k] for k in req)
        body += "".join("(," + pairs[k] + ")?" for k in opt)
    elif opt:
        # all-optional: alternate on the first key present, each chain
        # keeping declaration order for what follows
        chains = []
        for i, k in enumerate(opt):
            chain = pairs[k] + "".join(
                "(," + pairs[j] + ")?" for j in opt[i + 1:])
            chains.append(chain)
        body = "(" + "|".join(chains) + ")?"
    else:
        body = ""
    return r"\{" + body + r"\}"


def schema_to_regex(schema):
    """Lower a JSON-schema subset to a regex over *compact* JSON (no
    insignificant whitespace, ``json.dumps(separators=(',', ':'))``
    form).  Raises :class:`GrammarError` naming unsupported features.
    """
    return _schema_regex(schema, 0)


# ---------------------------------------------------------------------------
# GrammarSpec: the validated request-level grammar object
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GrammarSpec:
    """A validated grammar riding a request (alongside SamplingParams).

    ``kind`` is ``"regex"`` or ``"json_schema"``; ``source`` is the
    canonical text (the pattern, or the sorted-key JSON dump of the
    schema) and keys the engine's compile cache; ``pattern`` is the
    effective regex the compiler consumes.  Construction validates
    eagerly (parse / lowering), so a bad grammar raises
    :class:`GrammarError` at the gateway, before anything queues.
    """

    kind: str
    source: str
    pattern: str

    @classmethod
    def regex(cls, pattern):
        pattern = str(pattern)
        compile_regex(pattern)     # validate eagerly
        return cls(kind="regex", source=pattern, pattern=pattern)

    @classmethod
    def json_schema(cls, schema):
        pattern = schema_to_regex(schema)
        compile_regex(pattern)
        return cls(kind="json_schema", source=_json_dump(schema),
                   pattern=pattern)

    @property
    def key(self):
        return (self.kind, self.source)


def as_grammar_spec(obj):
    """Coerce a request-level grammar value to a :class:`GrammarSpec`:
    a string is a regex, a dict is a JSON schema, a spec passes
    through."""
    if isinstance(obj, GrammarSpec):
        return obj
    if isinstance(obj, str):
        return GrammarSpec.regex(obj)
    if isinstance(obj, dict):
        return GrammarSpec.json_schema(obj)
    raise GrammarError(
        f"grammar must be a regex string, a JSON-schema object, or a "
        f"GrammarSpec, got {type(obj).__name__}")


# ---------------------------------------------------------------------------
# GrammarSlab: host master for the fixed-capacity device DFA slab
# ---------------------------------------------------------------------------


class GrammarSlab:
    """Fixed-capacity host master for the device-resident token-DFA
    tables.

    Row 0 is the reserved accept-all sentinel unconstrained lanes ride:
    every token legal, every transition a self-loop — masking with it
    is the identity (``where(True, x, floor)`` is bitwise ``x``), so a
    mixed constrained/free batch is one compiled program with zero cost
    to free lanes.  Compiled grammars install at refcounted offsets
    >= 1; installed rows store *global* next-state ids (grammar-local
    state + offset) so the engine advances lanes with one gather, and
    REJECT entries store 0 (the sentinel row) because legality is
    decided by the bitmask alone — a rejected gather must stay a valid
    row index for the lanes whose position is never emitted.

    Single-owner by contract: only the engine thread mutates the slab
    (PTA51x); the engine re-uploads when ``dirty``.
    """

    def __init__(self, capacity, vocab_size):
        capacity = int(capacity)
        if capacity < 2:
            raise ValueError(
                "grammar_max_states must be >= 2: row 0 is the "
                "reserved accept-all sentinel, grammars need rows >= 1")
        self.capacity = capacity
        self.vocab_size = int(vocab_size)
        words = (self.vocab_size + 31) // 32
        self.next = np.zeros((capacity, self.vocab_size), np.int32)
        self.mask = np.zeros((capacity, words), np.uint32)
        self.forced = np.full(capacity, -1, np.int32)
        self.popcount = np.zeros(capacity, np.int32)
        self.accepting = np.zeros(capacity, bool)
        self.mask[0] = _pack_mask(
            np.ones((1, self.vocab_size), bool))[0]
        self.popcount[0] = self.vocab_size
        self.dirty = True
        self._segments = {}    # key -> [offset, size, refs]

    @property
    def states_used(self):
        return 1 + sum(sz for _, sz, _ in self._segments.values())

    @property
    def grammars_installed(self):
        return len(self._segments)

    @property
    def device_bytes(self):
        return (self.next.nbytes + self.mask.nbytes
                + self.forced.nbytes)

    def offset(self, key):
        return self._segments[key][0]

    def installed(self, key):
        """True while ``key`` holds a live (refcount > 0) segment."""
        return key in self._segments

    def _alloc(self, size):
        taken = sorted((off, sz) for off, sz, _ in
                       self._segments.values())
        cur = 1
        for off, sz in taken:
            if off - cur >= size:
                break
            cur = off + sz
        if cur + size > self.capacity:
            raise RuntimeError(
                f"grammar slab exhausted: need {size} states, "
                f"{self.capacity - self.states_used} free of "
                f"{self.capacity} (raise grammar_max_states or retire "
                "constrained requests)")
        return cur

    def install(self, key, dfa):
        """Install (or re-reference) a compiled TokenDFA; returns the
        row offset of its start state."""
        seg = self._segments.get(key)
        if seg is not None:
            seg[2] += 1
            return seg[0]
        if dfa.vocab_size != self.vocab_size:
            raise ValueError(
                f"grammar compiled for vocab {dfa.vocab_size}, slab "
                f"holds vocab {self.vocab_size}")
        size = dfa.n_states
        off = self._alloc(size)
        self.next[off:off + size] = np.where(
            dfa.next_state >= 0, dfa.next_state + off, 0)
        self.mask[off:off + size] = dfa.mask
        self.forced[off:off + size] = dfa.forced
        self.popcount[off:off + size] = dfa.popcount
        self.accepting[off:off + size] = dfa.accepting
        self._segments[key] = [off, size, 1]
        self.dirty = True
        return off

    def release(self, key):
        """Drop one reference; frees the rows at refcount zero (the
        device arrays are refreshed lazily at the next install)."""
        seg = self._segments.get(key)
        if seg is None:
            return
        seg[2] -= 1
        if seg[2] <= 0:
            off, size, _ = self._segments.pop(key)
            self.next[off:off + size] = 0
            self.mask[off:off + size] = 0
            self.forced[off:off + size] = -1
            self.popcount[off:off + size] = 0
            self.accepting[off:off + size] = False
