"""Grammar-constrained decoding (structured generation).

Three layers: the grammar compiler (:mod:`.grammar` — regex / JSON
schema -> token-level DFA over the vocab), the engine integration
(per-lane DFA states in the donated scan carry + the logit mask fused
into ``sample_window``), and the constraint-aware drafter (forced-token
chains proposed ahead of n-gram drafts, see
``serving.drafter.forced_chain``).
"""

from .grammar import (          # noqa: F401
    REJECT,
    CharDFA,
    GrammarError,
    GrammarSlab,
    GrammarSpec,
    TokenDFA,
    as_grammar_spec,
    compile_grammar,
    compile_regex,
    schema_to_regex,
)

__all__ = [
    "REJECT",
    "CharDFA",
    "GrammarError",
    "GrammarSlab",
    "GrammarSpec",
    "TokenDFA",
    "as_grammar_spec",
    "compile_grammar",
    "compile_regex",
    "schema_to_regex",
]
