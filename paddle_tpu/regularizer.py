"""paddle.regularizer parity (ref: python/paddle/regularizer.py (U)).

L1/L2 weight decay attached via ParamAttr or the optimizer's
`weight_decay=` argument; the optimizer applies `loss_grad_term(p)` to each
gradient before the update (decoupled decay stays in AdamW)."""

from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def __call__(self, param_array):
        """Gradient contribution d(penalty)/d(param)."""
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param_array):
        return self.coeff * jnp.sign(param_array)

    def __repr__(self):
        return f"L1Decay(coeff={self.coeff})"


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, param_array):
        return self.coeff * param_array

    def __repr__(self):
        return f"L2Decay(coeff={self.coeff})"


__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]
