"""paddle.geometric parity (ref: python/paddle/geometric/ (U): segment ops +
message passing backed by CUDA scatter kernels). TPU-native:
jax.ops.segment_* — XLA lowers them to sorted-scatter, which is the TPU-
efficient form of the reference's atomics-based kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_call import apply
from ..tensor.creation import _as_t


def _seg(fn_name, jfn, x, segment_ids):
    xt, st = _as_t(x), _as_t(segment_ids)

    def f(a, ids):
        n = int(jnp.max(ids)) + 1 if not isinstance(
            ids, jax.core.Tracer) else None
        if n is None:
            raise ValueError(f"{fn_name}: segment_ids must be concrete "
                             f"(static segment count) under jit")
        ids32 = ids.astype(jnp.int32)
        out = jfn(a, ids32, num_segments=n)
        if fn_name in ("segment_max", "segment_min"):
            out = _fill_empty(out, ids32, n, a)
        return out

    return apply(f, xt, st, _op_name=fn_name)


def _fill_empty(out, ids32, n, data):
    """Reference convention: segments with no members read 0 (jax fills
    them with the dtype's ±max/min sentinel, which for ints is finite, so
    mask by member count, not isfinite; keep the input dtype)."""
    cnt = jax.ops.segment_sum(jnp.ones((ids32.shape[0],), jnp.int32), ids32,
                              num_segments=n)
    mask = (cnt > 0).reshape((-1,) + (1,) * (data.ndim - 1))
    return jnp.where(mask, out, jnp.zeros((), out.dtype))


def segment_sum(data, segment_ids, name=None):
    return _seg("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    xt, st = _as_t(data), _as_t(segment_ids)

    def f(a, ids):
        n = int(jnp.max(ids)) + 1
        ids = ids.astype(jnp.int32)
        s = jax.ops.segment_sum(a, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((a.shape[0],), a.dtype), ids,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (a.ndim - 1))

    return apply(f, xt, st, _op_name="segment_mean")


def segment_max(data, segment_ids, name=None):
    return _seg("segment_max", jax.ops.segment_max, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _seg("segment_min", jax.ops.segment_min, data, segment_ids)


def _scatter_reduce(msgs, dst, reduce_op, n):
    """Scatter-reduce messages onto n destination rows; empty rows -> 0
    (reference fill convention)."""
    dst32 = dst.astype(jnp.int32)
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, dst32, num_segments=n)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, dst32, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), msgs.dtype),
                                  dst32, num_segments=n)
        return s / jnp.maximum(cnt, 1.0).reshape(
            (-1,) + (1,) * (msgs.ndim - 1))
    if reduce_op == "max":
        out = jax.ops.segment_max(msgs, dst32, num_segments=n)
        return _fill_empty(out, dst32, n, msgs)
    if reduce_op == "min":
        out = jax.ops.segment_min(msgs, dst32, num_segments=n)
        return _fill_empty(out, dst32, n, msgs)
    raise ValueError(f"reduce_op {reduce_op!r}")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """ref send_u_recv: gather x[src], scatter-reduce onto dst."""
    xt = _as_t(x)
    st = _as_t(src_index)
    dt = _as_t(dst_index)

    def f(a, src, dst):
        msgs = a[src.astype(jnp.int32)]
        return _scatter_reduce(msgs, dst, reduce_op,
                               int(out_size) if out_size is not None
                               else a.shape[0])

    return apply(f, xt, st, dt, _op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """ref send_ue_recv: combine node features x[src] with edge features y,
    then scatter-reduce onto dst."""
    xt, yt = _as_t(x), _as_t(y)
    st, dt = _as_t(src_index), _as_t(dst_index)

    def f(a, e, src, dst):
        msgs = a[src.astype(jnp.int32)]
        if message_op == "add":
            msgs = msgs + e
        elif message_op == "sub":
            msgs = msgs - e
        elif message_op == "mul":
            msgs = msgs * e
        elif message_op == "div":
            msgs = msgs / e
        else:
            raise ValueError(f"message_op {message_op!r}")
        return _scatter_reduce(msgs, dst, reduce_op,
                               int(out_size) if out_size is not None
                               else a.shape[0])

    return apply(f, xt, yt, st, dt, _op_name="send_ue_recv")


__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv"]
