"""paddle.linalg namespace (ref: python/paddle/linalg.py (U))."""

from ..tensor.linalg import (
    matmul, dot, cross, norm, vector_norm, matrix_norm, cond, det, slogdet,
    inv, pinv, svd, svdvals, qr, eig, eigh, eigvals, eigvalsh, cholesky,
    cholesky_solve, solve, triangular_solve, lstsq, lu, matrix_power,
    matrix_rank, multi_dot, pca_lowrank, corrcoef, cov, householder_product,
    lu_unpack, matrix_exp, ormqr, svd_lowrank, cdist, pdist,
)
