"""paddle.static.amp (ref: python/paddle/static/amp/decorator.py (U) —
`decorate(optimizer)` returns an OptimizerWithMixedPrecision whose
minimize() rewrites the program with casts and dynamic loss scaling).

TPU-native: the rewrite machinery is the static meta-optimizer
(fleet/meta_optimizers/static_meta_optimizer.py); this module is the
reference's non-fleet entry point to the same pass. fp16 gets dynamic
loss scaling compiled into the train program; bf16 (TPU default half
type, pass dtype='bfloat16') needs none."""

from __future__ import annotations

__all__ = ["decorate", "AutoMixedPrecisionLists", "CustomOpLists"]


class AutoMixedPrecisionLists:
    """ref AutoMixedPrecisionLists: custom white/black op-name lists merged
    over the framework defaults (amp/auto_cast.py WHITE_LIST/BLACK_LIST)."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None, dtype="float16"):
        self.custom_white_list = set(custom_white_list or ())
        self.custom_black_list = set(custom_black_list or ())
        if custom_black_varnames:
            raise NotImplementedError(
                "custom_black_varnames (per-variable amp exclusion) is not "
                "supported; use custom_black_list with op names")
        self.dtype = dtype


CustomOpLists = AutoMixedPrecisionLists


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_pure_fp16=False,
             use_fp16_guard=None, dtype=None, level="O1",
             master_weight=None):
    """ref static.amp.decorate: wrap `optimizer` so minimize() applies the
    mixed-precision program rewrite. Returns the static meta-optimizer
    with ONLY the amp strategy enabled — composes with
    fleet.distributed_optimizer strategies if used there instead.

    dtype resolution: the explicit `dtype` argument wins; otherwise
    `amp_lists.dtype`; default float16 (the reference default).
    `use_fp16_guard` (block-scoped fp16 regions) and `master_weight` are
    accepted for signature parity but moot by design here: the cast
    rewrite is op-list-scoped, and Adam-family optimizers always keep f32
    master state (the multi_precision path)."""
    from ..distributed.fleet.base.distributed_strategy import (
        DistributedStrategy,
    )
    from ..distributed.fleet.meta_optimizers.static_meta_optimizer import (
        StaticMetaOptimizer,
    )

    if dtype is None:
        dtype = getattr(amp_lists, "dtype", None) or "float16"
    strategy = DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs = {
        "use_bf16": str(dtype) in ("bfloat16", "uint16", "paddle.bfloat16"),
        "init_loss_scaling": float(init_loss_scaling),
        "incr_every_n_steps": int(incr_every_n_steps),
        "decr_every_n_nan_or_inf": int(decr_every_n_nan_or_inf),
        "incr_ratio": float(incr_ratio),
        "decr_ratio": float(decr_ratio),
        "use_dynamic_loss_scaling": bool(use_dynamic_loss_scaling),
        "use_pure_fp16": bool(use_pure_fp16 or level == "O2"),
        "custom_white_list": sorted(
            getattr(amp_lists, "custom_white_list", ()) or ()),
        "custom_black_list": sorted(
            getattr(amp_lists, "custom_black_list", ()) or ()),
    }
    wrapped = StaticMetaOptimizer(optimizer, strategy)
    return wrapped
