"""static.nn — the classic static-graph layer helpers (ref: the paddle 1.x
`fluid.layers`/`static.nn` family). Parameters are created eagerly
(concrete) and captured by the recorded graph as constants; the data path
stays symbolic."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Parameter
from ..nn import functional as F


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """ref static.nn.fc: flatten trailing dims, affine, optional act.
    Weights draw from the framework RNG (paddle.seed-respecting, distinct
    per call)."""
    if weight_attr is not None or bias_attr is not None:
        raise NotImplementedError(
            "static.nn.fc: weight_attr/bias_attr initializers are not "
            "supported; build the model with paddle_tpu.nn layers instead")
    import jax

    from ..core import random as random_state

    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    k = 1.0 / np.sqrt(in_dim)
    w = Parameter(np.asarray(jax.random.uniform(
        random_state.next_key(), (in_dim, size), np.float32, -k, k)))
    b = Parameter(np.zeros((size,), np.float32))
    if x.ndim > num_flatten_dims + 1:
        from ..tensor.manipulation import reshape

        # -1 on the leading (possibly dynamic-batch) dim: the recorded
        # reshape must not bake in the build-time placeholder size
        tail = list(x.shape[1:num_flatten_dims]) + [in_dim]
        x = reshape(x, [-1] + tail)
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def batch_norm(x, **kwargs):
    raise NotImplementedError(
        "static.nn.batch_norm: build the model with paddle_tpu.nn layers "
        "and stage it via static mode or jit.to_static")


# --------------------------------------------------------------------------
# Control-flow staging (ref static.nn.cond/while_loop/case/switch_case —
# the dy2static ControlFlow ops, SURVEY.md §2.1 N27 / §2.2 P8). TPU-native
# stance: cond builds BOTH branches (exactly like the reference's static
# ConditionalBlock recording) and the outputs are selected by the traced
# predicate — XLA-friendly, differentiable, and valid in eager mode, under
# jit/to_static, and inside static Program recording. while_loop lowers to
# lax.while_loop (forward-only, like compiled loops everywhere on TPU).


def _flatten_rets(res):
    """Flatten a branch return (Tensor | nested tuple/list of Tensors |
    None) into (leaves, rebuild)."""
    from ..tensor.creation import _as_t

    if res is None:
        return [], lambda leaves: None
    if isinstance(res, (tuple, list)):
        ctor = type(res)
        subs = [_flatten_rets(r) for r in res]
        sizes = []
        leaves = []
        for ls, _ in subs:
            sizes.append(len(ls))
            leaves.extend(ls)

        def rebuild(vals):
            out, off = [], 0
            for (ls, rb), n in zip(subs, sizes):
                out.append(rb(vals[off:off + n]))
                off += n
            return ctor(out)

        return leaves, rebuild
    t = _as_t(res)
    return [t], lambda vals: vals[0]


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """ref static.nn.cond: run `true_fn()` where pred holds, `false_fn()`
    otherwise. In eager mode (concrete predicate) exactly ONE branch
    executes — the reference's dygraph semantics, with exact gradients.
    Under jit tracing / static recording both branch graphs are built
    (the reference records both ConditionalBlocks too) and the outputs
    are selected by the traced predicate; the untaken branch's cotangent
    is zeroed AT THE SELECT, but its ops still see a zero cotangent, so a
    branch guarding against non-differentiable points (e.g. sqrt at 0)
    can still propagate NaN under tracing — the standard XLA select
    trade-off. Route such guards through the predicate's values instead
    (mask the INPUT, not the output)."""
    import jax
    import jax.numpy as jnp

    from ..core.op_call import apply
    from ..core.tensor import Tensor
    from ..tensor.creation import _as_t
    from .graph import _SymArr

    if true_fn is None or false_fn is None:
        raise ValueError("cond requires both true_fn and false_fn")
    pred_t = _as_t(pred)
    pd = pred_t._data
    if not isinstance(pd, (_SymArr, jax.core.Tracer)):
        # eager: execute only the taken branch (exact reference dygraph
        # semantics; no untaken-branch gradient artifacts)
        return true_fn() if bool(np.asarray(pd).reshape(())) else false_fn()
    t_res = true_fn()
    f_res = false_fn()
    t_leaves, rebuild = _flatten_rets(t_res)
    f_leaves, _ = _flatten_rets(f_res)
    if len(t_leaves) != len(f_leaves):
        raise ValueError(
            f"cond branches return different structures: "
            f"{len(t_leaves)} vs {len(f_leaves)} tensors")
    outs = []
    for a, b in zip(t_leaves, f_leaves):
        if tuple(a.shape) != tuple(b.shape):
            raise ValueError(
                f"cond branch outputs must have matching shapes, got "
                f"{tuple(a.shape)} vs {tuple(b.shape)}")
        if str(a.dtype) != str(b.dtype):
            raise ValueError(
                f"cond branch outputs must have matching dtypes, got "
                f"{a.dtype} vs {b.dtype} (the select would silently "
                "promote; cast one branch explicitly)")
        outs.append(apply(
            lambda p, x, y: jnp.where(p.reshape(()).astype(bool), x, y),
            pred_t, a, b, _op_name="cond"))
    return rebuild(outs)


def case(pred_fn_pairs, default=None, name=None):
    """ref static.nn.case: first predicate that holds wins (chained
    cond selects)."""
    if not pred_fn_pairs:
        raise ValueError("case requires at least one (pred, fn) pair")
    if default is None:
        *rest, (last_p, last_fn) = list(pred_fn_pairs)
        default = last_fn
    else:
        rest = list(pred_fn_pairs)
    out = default()
    for p, fn in reversed(rest):
        out = cond(p, fn, (lambda o: lambda: o)(out))
    return out


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref static.nn.switch_case: select a branch by integer index.
    branch_fns: dict {index: fn} or list of (index, fn) / fns."""
    from ..tensor.creation import _as_t

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = sorted(
            (i, f) if not isinstance(f, (tuple, list)) else tuple(f)
            for i, f in enumerate(branch_fns))
    idx = _as_t(branch_index)
    if default is None:
        # ref contract: out-of-range indices dispatch to the MAX-index fn
        default = items[-1][1]
    out = default()
    for i, fn in reversed(items):
        out = cond(idx == i, fn, (lambda o: lambda: o)(out))
    return out


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """ref static.nn.while_loop: `while cond(*vars): vars = body(*vars)`
    compiled as ONE lax.while_loop — data-dependent trip counts stage
    under jit and into static Programs (no Python-level unrolling).
    Forward-only (XLA while has no reverse-mode); closures may capture
    parameters/constants, but symbolic (placeholder-derived) tensors must
    be passed through loop_vars."""
    from jax import lax

    from ..core import tape as _tape
    from ..core.op_call import apply
    from ..core.tensor import Tensor as _T
    from ..tensor.creation import _as_t

    if not isinstance(loop_vars, (tuple, list)) or not loop_vars:
        raise ValueError("while_loop expects a non-empty list of loop_vars")
    ctor = type(loop_vars)
    tensors = [_as_t(v) for v in loop_vars]
    cond_fn, body_fn = cond, body

    import jax

    def c(carry):
        with _tape.no_grad():
            r = cond_fn(*[_T(a) for a in carry])
        return _as_t(r)._data.reshape(()).astype(bool)

    def b(carry):
        with _tape.no_grad():
            out = body_fn(*[_T(a) for a in carry])
        if not isinstance(out, (tuple, list)):
            out = (out,)
        if len(out) != len(carry):
            raise ValueError(
                f"while_loop body returned {len(out)} values for "
                f"{len(carry)} loop_vars")
        res = []
        for o, a in zip(out, carry):
            oa = _as_t(o)._data
            if oa.shape != a.shape or oa.dtype != a.dtype:
                raise ValueError(
                    f"while_loop body changed a loop var from "
                    f"{a.shape}/{a.dtype} to {oa.shape}/{oa.dtype} "
                    "(loop-carried values must keep shape and dtype)")
            res.append(oa)
        return tuple(res)

    # forward-only CONTRACT made explicit to jax: an enclosing jax.vjp
    # (the to_static grad-aware path linearizes the whole forward) must
    # not linearize through lax.while_loop (it has no reverse rule and
    # its jvp path crashes on closure-heavy bodies). closure_convert
    # surfaces the body's closed-over values (params!) as explicit
    # arguments, and stop_gradient on ALL of them makes the loop a
    # constant to the outer linearization — exactly the stop_gradient
    # semantics the Tensor level already declares on the outputs.
    def f(*arrs):
        def base(*arrs_t):
            return lax.while_loop(c, b, tuple(arrs_t))

        conv, consts = jax.closure_convert(base, *arrs)
        return conv(*[lax.stop_gradient(a) for a in arrs],
                    *[lax.stop_gradient(x) for x in consts])

    outs = apply(f, *tensors, _op_name="while_loop")
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    for o in outs:
        o.stop_gradient = True  # forward-only: XLA while has no vjp
    return ctor(outs)
