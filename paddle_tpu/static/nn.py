"""static.nn — the classic static-graph layer helpers (ref: the paddle 1.x
`fluid.layers`/`static.nn` family). Parameters are created eagerly
(concrete) and captured by the recorded graph as constants; the data path
stays symbolic."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Parameter
from ..nn import functional as F


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """ref static.nn.fc: flatten trailing dims, affine, optional act.
    Weights draw from the framework RNG (paddle.seed-respecting, distinct
    per call)."""
    if weight_attr is not None or bias_attr is not None:
        raise NotImplementedError(
            "static.nn.fc: weight_attr/bias_attr initializers are not "
            "supported; build the model with paddle_tpu.nn layers instead")
    import jax

    from ..core import random as random_state

    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    k = 1.0 / np.sqrt(in_dim)
    w = Parameter(np.asarray(jax.random.uniform(
        random_state.next_key(), (in_dim, size), np.float32, -k, k)))
    b = Parameter(np.zeros((size,), np.float32))
    if x.ndim > num_flatten_dims + 1:
        from ..tensor.manipulation import reshape

        # -1 on the leading (possibly dynamic-batch) dim: the recorded
        # reshape must not bake in the build-time placeholder size
        tail = list(x.shape[1:num_flatten_dims]) + [in_dim]
        x = reshape(x, [-1] + tail)
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def batch_norm(x, **kwargs):
    raise NotImplementedError(
        "static.nn.batch_norm: build the model with paddle_tpu.nn layers "
        "and stage it via static mode or jit.to_static")
