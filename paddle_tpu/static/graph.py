"""A REAL (minimal) static-graph mode, TPU-natively (ref: the
Program/Executor stack, SURVEY.md §2.1 N10/N11 — there the graph is a
ProgramDesc interpreted by InterpreterCore; here "the jaxpr IS the program"
is made literal).

Design: every eager op already funnels through `core.op_call.apply`. Under
`paddle.enable_static()`, `static.data(...)` returns a placeholder Tensor
whose `_data` is a symbolic shape/dtype carrier; `apply` (via the handler
installed below) sees a symbolic input and, instead of executing, RECORDS a
graph node (out shapes from `jax.eval_shape` — the InferMeta analog) and
returns symbolic outputs. `Executor.run(feed, fetch_list)` evaluates the
recorded DAG as ONE `jax.jit`-compiled function of the feeds — concrete
tensors captured along the way (parameters, constants) ride in as closure
constants, exactly like a frozen inference program.

Scope (documented): forward graphs — build, run, save/load for serving.
Static-mode training (append_backward / minimize) remains out of scope;
training is the dygraph + jit.TrainStep path (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import op_call as _op_call


class StaticGraphError(RuntimeError):
    pass


class SymbolicDataError(StaticGraphError, AttributeError):
    """Touching concrete data on a symbolic tensor. AttributeError-
    compatible so hasattr/getattr feature detection keeps working."""


class _SymArr:
    """Symbolic value: shape/dtype (for InferMeta-style queries) + the
    producing graph node. Any attempt to touch concrete data raises."""

    __slots__ = ("aval", "node", "out_idx", "feed_name", "orig_shape")

    def __init__(self, aval, node=None, out_idx=0, feed_name=None):
        self.aval = aval
        self.node = node
        self.out_idx = out_idx
        self.feed_name = feed_name
        self.orig_shape = None

    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    def _concrete_needed(self, what):
        # NOT an AttributeError: numpy/python protocol machinery must see
        # a loud failure, not an absent-method fallback
        raise StaticGraphError(
            f"{what} needs concrete data, but this Tensor is symbolic "
            "(inside a static Program). Run it through Executor.run, or "
            "use ops routed through the standard dispatch.")

    # data-access protocols raise loudly when CALLED (defined explicitly —
    # were they routed through __getattr__'s AttributeError, numpy et al.
    # would silently fall back to object arrays)
    def __array__(self, *a, **k):
        self._concrete_needed("__array__")

    def __float__(self):
        self._concrete_needed("__float__")

    def __int__(self):
        self._concrete_needed("__int__")

    def __bool__(self):
        self._concrete_needed("__bool__")

    def __index__(self):
        self._concrete_needed("__index__")

    def __len__(self):
        self._concrete_needed("__len__")

    def __iter__(self):
        self._concrete_needed("__iter__")

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            # protocol probes (deepcopy/pickle/...) fall back quietly
            raise AttributeError(name)
        raise SymbolicDataError(
            f"'{name}' needs concrete data, but this Tensor is symbolic "
            "(inside a static Program). Run it through Executor.run, or "
            "use ops routed through the standard dispatch.")

    def __repr__(self):
        src = self.feed_name or (self.node.op_name if self.node else "?")
        return f"SymArr({self.aval.shape}, {self.aval.dtype}, from={src})"


class _Node:
    """One recorded op: fn(*inputs, **kwargs) -> n outputs."""

    __slots__ = ("fn", "inputs", "kwargs", "n_out", "op_name")

    def __init__(self, fn, inputs, kwargs, n_out, op_name):
        self.fn = fn
        self.inputs = inputs      # list of _SymArr | concrete jax arrays
        self.kwargs = kwargs
        self.n_out = n_out
        self.op_name = op_name


class Program:
    """Holds the placeholders created under its guard (the graph itself is
    the web of _Node objects reachable from fetched values)."""

    def __init__(self):
        self.placeholders = {}   # name -> Tensor (symbolic)

    def global_block(self):
        return self

    @property
    def vars(self):
        return dict(self.placeholders)

    def clone(self, for_test=False):
        return self


_state = {"static": False, "main": Program(), "startup": Program()}


def enable_static():
    _state["static"] = True
    _op_call.set_static_handler(_static_apply)


def disable_static():
    _state["static"] = False
    _op_call.set_static_handler(None)


def in_static_mode():
    return _state["static"]


def default_main_program():
    return _state["main"]


def default_startup_program():
    return _state["startup"]


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self._main = main_program
        self._startup = startup_program or Program()

    def __enter__(self):
        self._saved = (_state["main"], _state["startup"])
        _state["main"], _state["startup"] = self._main, self._startup
        return self

    def __exit__(self, *exc):
        _state["main"], _state["startup"] = self._saved
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder (ref static.data): symbolic input of the main program.
    Leading None/-1 dims become 1 for tracing (dynamic batch is re-traced
    per concrete feed shape by Executor)."""
    if not _state["static"]:
        raise StaticGraphError("static.data requires paddle.enable_static()")
    norm = tuple(1 if (s is None or s < 0) else int(s) for s in shape)
    aval = jax.ShapeDtypeStruct(norm, jnp.dtype(dtype))
    t = Tensor.__new__(Tensor)
    t._data = _SymArr(aval, feed_name=name)
    t._data.orig_shape = tuple(None if (s is None or s < 0) else int(s)
                               for s in shape)
    t.grad = None
    t.stop_gradient = True
    t._tape_node = None
    t.name = name
    t.persistable = False
    t.trainable = False
    _state["main"].placeholders[name] = t
    return t


def _is_sym(x):
    return isinstance(x, Tensor) and isinstance(x._data, _SymArr)


def _static_apply(fn, args, kwargs, op_name):
    """Handler installed into op_call.apply under static mode. Returns None
    when no symbolic input is involved (pure eager constants); otherwise
    records a node and returns symbolic output Tensor(s)."""
    if not any(_is_sym(a) for a in args):
        return None
    inputs = []
    for i, a in enumerate(args):
        if _is_sym(a):
            inputs.append(a._data)
        elif isinstance(a, Tensor):
            inputs.append(a._data)
        else:
            inputs.append(a)

    # InferMeta: abstract-evaluate with symbolic avals at sym positions
    sym_idx = [i for i, x in enumerate(inputs) if isinstance(x, _SymArr)]

    def probe(*sym_vals):
        full = list(inputs)
        for j, i in enumerate(sym_idx):
            full[i] = sym_vals[j]
        return fn(*full, **kwargs)

    sym_avals = [inputs[i].aval for i in sym_idx]
    try:
        out_sds = jax.eval_shape(probe, *sym_avals)
    except StaticGraphError:
        raise
    except Exception as e:
        raise StaticGraphError(
            f"op {op_name or getattr(fn, '__name__', 'op')!r} cannot be "
            f"staged into the static program: {type(e).__name__}: {e}"
        ) from e
    multi = isinstance(out_sds, (tuple, list))
    outs_flat = list(out_sds) if multi else [out_sds]
    # namedtuples (e.g. linalg results) collapse to plain tuple, matching
    # the eager path's _out_type
    container = tuple if hasattr(out_sds, "_fields") else type(out_sds)
    node = _Node(fn, inputs, kwargs, len(outs_flat),
                 op_name or getattr(fn, "__name__", "op"))
    out_tensors = []
    for i, sds in enumerate(outs_flat):
        t = Tensor.__new__(Tensor)
        t._data = _SymArr(jax.ShapeDtypeStruct(sds.shape, sds.dtype),
                          node=node, out_idx=i)
        t.grad = None
        t.stop_gradient = True
        t._tape_node = None
        t.name = None
        t.persistable = False
        t.trainable = False
        out_tensors.append(t)
    if multi:
        return container(out_tensors)
    return out_tensors[0]


def _evaluate(fetch_syms, feed_values):
    """Evaluate the DAG for the given fetches. feed_values: name->array.
    Memoized over nodes; runs under whatever trace calls it (Executor jits
    it)."""
    node_memo = {}

    def feed_of(sym):
        try:
            return feed_values[sym.feed_name]
        except KeyError:
            raise StaticGraphError(
                f"missing feed for placeholder {sym.feed_name!r}")

    def value_of(sym):
        """Iterative post-order over producers — a sequential graph deeper
        than the interpreter recursion limit must still evaluate."""
        if sym.feed_name is not None:
            return feed_of(sym)
        if sym.node is None:
            raise StaticGraphError("symbolic value with no producer")
        stack = [sym.node]
        while stack:
            n = stack[-1]
            if id(n) in node_memo:
                stack.pop()
                continue
            pending = [x.node for x in n.inputs
                       if isinstance(x, _SymArr) and x.feed_name is None
                       and x.node is not None and id(x.node) not in node_memo]
            if pending:
                stack.extend(pending)
                continue
            stack.pop()
            full = []
            for x in n.inputs:
                if isinstance(x, _SymArr):
                    full.append(feed_of(x) if x.feed_name is not None
                                else node_memo[id(x.node)][x.out_idx])
                else:
                    full.append(x)
            out = n.fn(*full, **n.kwargs)
            node_memo[id(n)] = list(out) if isinstance(out, (tuple, list)) \
                else [out]
        return node_memo[id(sym.node)][sym.out_idx]

    return [value_of(s) for s in fetch_syms]


class Executor:
    """ref static.Executor: compiles + runs the fetched subgraph as ONE
    XLA program per (feed shapes) signature."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        feed = feed or {}
        fetch_list = fetch_list or []
        syms = []
        for f in fetch_list:
            if not _is_sym(f):
                raise StaticGraphError(
                    "fetch_list entries must be static-program Tensors")
            syms.append(f._data)
        feed_names = sorted(feed)
        feed_arrays = [jnp.asarray(np.asarray(feed[k])) for k in feed_names]
        key = (tuple(id(s) for s in syms), tuple(feed_names),
               tuple((a.shape, str(a.dtype)) for a in feed_arrays))
        if key not in self._cache:
            def eval_fn(*arrays):
                vals = dict(zip(feed_names, arrays))
                return tuple(_evaluate(syms, vals))

            self._cache[key] = jax.jit(eval_fn)
        outs = self._cache[key](*feed_arrays)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """ref static.save_inference_model: export the fetched subgraph as the
    same StableHLO artifact jit.save writes — loadable by jit.load AND
    servable by paddle.inference.create_predictor."""
    import os
    import pickle

    if not isinstance(feed_vars, (list, tuple)):
        feed_vars = [feed_vars]
    if not isinstance(fetch_vars, (list, tuple)):
        fetch_vars = [fetch_vars]
    names = []
    for v in feed_vars:
        if not (_is_sym(v) and v._data.feed_name):
            raise StaticGraphError("feed_vars must be static.data placeholders")
        names.append(v._data.feed_name)
    syms = [v._data for v in fetch_vars]

    def infer_fn(state_arrays, *arg_arrays):
        del state_arrays  # graph constants ride in the closure
        vals = dict(zip(names, arg_arrays))
        return tuple(_evaluate(syms, vals))

    # dynamic (None/-1) placeholder dims export as SYMBOLIC dims so the
    # served program accepts any size there (batch polymorphism)
    spec_shapes = []
    example = []
    dynamic = any(v._data.orig_shape and None in v._data.orig_shape
                  for v in feed_vars)
    sym_dims = {}
    for v in feed_vars:
        orig = v._data.orig_shape or v._data.aval.shape
        dims = []
        for ax, d in enumerate(orig):
            if d is None:
                key = f"d{len(sym_dims)}"
                if key not in sym_dims:
                    (sym_dims[key],) = jax.export.symbolic_shape(key)
                dims.append(sym_dims[key])
            else:
                dims.append(int(d))
        example.append(jax.ShapeDtypeStruct(tuple(dims), v._data.aval.dtype)
                       if dynamic else
                       jnp.zeros(tuple(dims), v._data.aval.dtype))
        spec_shapes.append([None if d is None else int(d) for d in orig])
    exported = jax.export.export(jax.jit(infer_fn))([], *example)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    from ..framework.io import save as fsave
    from ..jit.api import write_artifact

    fsave({}, path_prefix + ".pdiparams")
    write_artifact(
        path_prefix, exported,
        [(shape, str(np.dtype(v._data.aval.dtype)))
         for shape, v in zip(spec_shapes, feed_vars)],
        names, [])


def load_inference_model(path_prefix, executor=None, **kwargs):
    """ref static.load_inference_model -> (program, feed_names,
    fetch_targets); here the 'program' is the loaded TranslatedLayer."""
    from ..jit.api import load as jit_load

    layer = jit_load(path_prefix)
    return layer, list(getattr(layer, "_input_names", [])), layer
