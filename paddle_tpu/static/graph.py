"""A REAL (minimal) static-graph mode, TPU-natively (ref: the
Program/Executor stack, SURVEY.md §2.1 N10/N11 — there the graph is a
ProgramDesc interpreted by InterpreterCore; here "the jaxpr IS the program"
is made literal).

Design: every eager op already funnels through `core.op_call.apply`. Under
`paddle.enable_static()`, `static.data(...)` returns a placeholder Tensor
whose `_data` is a symbolic shape/dtype carrier; `apply` (via the handler
installed below) sees a symbolic input and, instead of executing, RECORDS a
graph node (out shapes from `jax.eval_shape` — the InferMeta analog) and
returns symbolic outputs. `Executor.run(feed, fetch_list)` evaluates the
recorded DAG as ONE `jax.jit`-compiled function of the feeds — concrete
tensors captured along the way (parameters, constants) ride in as closure
constants, exactly like a frozen inference program.

Scope: forward graphs — build, run, save/load for serving — PLUS minimal
static-mode training (SURVEY.md §2.2 P7, ref static.append_backward +
Optimizer.minimize over the Program): `opt.minimize(loss)` registers a
train op on the main Program; `Executor.run` then promotes the parameters
captured in the loss's DAG from closure constants to traced inputs,
differentiates the recorded graph with `jax.value_and_grad` through
`_evaluate`, applies the optimizer's functional update (`_update_for`,
the same math jit.TrainStep compiles), and writes the new arrays back
into the live Parameter tensors — the reference's canonical
`exe.run(startup); exe.run(main, feed, [loss])` loop trains. The static
meta-optimizer stack (P20) plugs in here too: _run_train honors the
recompute/loss-scaling/gradient-merge hooks installed by
fleet.StaticMetaOptimizer.minimize (see that module). Serious training
remains the dygraph + jit.TrainStep path (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import op_call as _op_call


class StaticGraphError(RuntimeError):
    pass


class SymbolicDataError(StaticGraphError, AttributeError):
    """Touching concrete data on a symbolic tensor. AttributeError-
    compatible so hasattr/getattr feature detection keeps working."""


class _SymArr:
    """Symbolic value: shape/dtype (for InferMeta-style queries) + the
    producing graph node. Any attempt to touch concrete data raises."""

    __slots__ = ("aval", "node", "out_idx", "feed_name", "orig_shape",
                 "program")

    def __init__(self, aval, node=None, out_idx=0, feed_name=None):
        self.aval = aval
        self.node = node
        self.out_idx = out_idx
        self.feed_name = feed_name
        self.orig_shape = None
        self.program = None   # owning Program (set on feed placeholders)

    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    def _concrete_needed(self, what):
        # NOT an AttributeError: numpy/python protocol machinery must see
        # a loud failure, not an absent-method fallback
        raise StaticGraphError(
            f"{what} needs concrete data, but this Tensor is symbolic "
            "(inside a static Program). Run it through Executor.run, or "
            "use ops routed through the standard dispatch.")

    # data-access protocols raise loudly when CALLED (defined explicitly —
    # were they routed through __getattr__'s AttributeError, numpy et al.
    # would silently fall back to object arrays)
    def __array__(self, *a, **k):
        self._concrete_needed("__array__")

    def __float__(self):
        self._concrete_needed("__float__")

    def __int__(self):
        self._concrete_needed("__int__")

    def __bool__(self):
        self._concrete_needed("__bool__")

    def __index__(self):
        self._concrete_needed("__index__")

    def __len__(self):
        self._concrete_needed("__len__")

    def __iter__(self):
        self._concrete_needed("__iter__")

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            # protocol probes (deepcopy/pickle/...) fall back quietly
            raise AttributeError(name)
        raise SymbolicDataError(
            f"'{name}' needs concrete data, but this Tensor is symbolic "
            "(inside a static Program). Run it through Executor.run, or "
            "use ops routed through the standard dispatch.")

    def __repr__(self):
        src = self.feed_name or (self.node.op_name if self.node else "?")
        return f"SymArr({self.aval.shape}, {self.aval.dtype}, from={src})"


class _ParamRef:
    """A trainable Parameter captured into the recorded graph. Kept as a
    live reference (not a frozen array) so (a) Executor.run always reads
    the CURRENT value and (b) the training path can promote it to a traced
    input and write the updated array back."""

    __slots__ = ("t",)

    def __init__(self, t):
        self.t = t


class _Node:
    """One recorded op: fn(*inputs, **kwargs) -> n outputs."""

    __slots__ = ("fn", "inputs", "kwargs", "n_out", "op_name", "out_avals")

    def __init__(self, fn, inputs, kwargs, n_out, op_name, out_avals=()):
        self.fn = fn
        self.inputs = inputs      # list of _SymArr | _ParamRef | jax arrays
        self.kwargs = kwargs
        self.n_out = n_out
        self.op_name = op_name
        self.out_avals = out_avals   # ShapeDtypeStructs (graph doctor)


class Program:
    """Holds the placeholders created under its guard (the graph itself is
    the web of _Node objects reachable from fetched values)."""

    def __init__(self):
        self.placeholders = {}   # name -> Tensor (symbolic)
        self.nodes = []          # creation-order op record (graph doctor)
        self._train_op = None    # (loss Tensor, optimizer) set by minimize

    def global_block(self):
        return self

    @property
    def vars(self):
        return dict(self.placeholders)

    def clone(self, for_test=False):
        if for_test and self._train_op is not None:
            # ref Program.clone(for_test=True): strip training ops
            c = Program()
            c.placeholders = dict(self.placeholders)
            c.nodes = list(self.nodes)
            return c
        return self


_state = {"static": False, "main": Program(), "startup": Program()}


def enable_static():
    _state["static"] = True
    _op_call.set_static_handler(_static_apply)


def disable_static():
    _state["static"] = False
    _op_call.set_static_handler(None)


def in_static_mode():
    return _state["static"]


def default_main_program():
    return _state["main"]


def default_startup_program():
    return _state["startup"]


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self._main = main_program
        self._startup = startup_program or Program()

    def __enter__(self):
        self._saved = (_state["main"], _state["startup"])
        _state["main"], _state["startup"] = self._main, self._startup
        return self

    def __exit__(self, *exc):
        _state["main"], _state["startup"] = self._saved
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder (ref static.data): symbolic input of the main program.
    Leading None/-1 dims become 1 for tracing (dynamic batch is re-traced
    per concrete feed shape by Executor)."""
    if not _state["static"]:
        raise StaticGraphError("static.data requires paddle.enable_static()")
    norm = tuple(1 if (s is None or s < 0) else int(s) for s in shape)
    aval = jax.ShapeDtypeStruct(norm, jnp.dtype(dtype))
    t = Tensor.__new__(Tensor)
    t._data = _SymArr(aval, feed_name=name)
    t._data.orig_shape = tuple(None if (s is None or s < 0) else int(s)
                               for s in shape)
    t._data.program = _state["main"]
    t.grad = None
    t.stop_gradient = True
    t._tape_node = None
    t.name = name
    t.persistable = False
    t.trainable = False
    _state["main"].placeholders[name] = t
    return t


def _is_sym(x):
    return isinstance(x, Tensor) and isinstance(x._data, _SymArr)


def _static_apply(fn, args, kwargs, op_name):
    """Handler installed into op_call.apply under static mode. Returns None
    when no symbolic input is involved (pure eager constants); otherwise
    records a node and returns symbolic output Tensor(s)."""
    if not any(_is_sym(a) for a in args):
        return None
    inputs = []
    for i, a in enumerate(args):
        if _is_sym(a):
            inputs.append(a._data)
        elif isinstance(a, Tensor):
            # trainable params stay LIVE references so the training path
            # can promote them to traced inputs (and plain re-runs see
            # updated values); frozen tensors ride as closure constants
            if not getattr(a, "stop_gradient", True):
                inputs.append(_ParamRef(a))
            else:
                inputs.append(a._data)
        else:
            inputs.append(a)

    # InferMeta: abstract-evaluate with symbolic avals at sym positions
    sym_idx = [i for i, x in enumerate(inputs) if isinstance(x, _SymArr)]

    def probe(*sym_vals):
        full = [x.t._data if isinstance(x, _ParamRef) else x
                for x in inputs]
        for j, i in enumerate(sym_idx):
            full[i] = sym_vals[j]
        return fn(*full, **kwargs)

    sym_avals = [inputs[i].aval for i in sym_idx]
    try:
        out_sds = jax.eval_shape(probe, *sym_avals)
    except StaticGraphError:
        raise
    except Exception as e:
        raise StaticGraphError(
            f"op {op_name or getattr(fn, '__name__', 'op')!r} cannot be "
            f"staged into the static program: {type(e).__name__}: {e}"
        ) from e
    multi = isinstance(out_sds, (tuple, list))
    outs_flat = list(out_sds) if multi else [out_sds]
    # namedtuples (e.g. linalg results) collapse to plain tuple, matching
    # the eager path's _out_type
    container = tuple if hasattr(out_sds, "_fields") else type(out_sds)
    node = _Node(fn, inputs, kwargs, len(outs_flat),
                 op_name or getattr(fn, "__name__", "op"),
                 out_avals=tuple(outs_flat))
    _state["main"].nodes.append(node)
    out_tensors = []
    for i, sds in enumerate(outs_flat):
        t = Tensor.__new__(Tensor)
        t._data = _SymArr(jax.ShapeDtypeStruct(sds.shape, sds.dtype),
                          node=node, out_idx=i)
        t.grad = None
        t.stop_gradient = True
        t._tape_node = None
        t.name = None
        t.persistable = False
        t.trainable = False
        out_tensors.append(t)
    if multi:
        return container(out_tensors)
    return out_tensors[0]


def _run_dag(target_nodes, feed_values, param_values=None, seed=None):
    """Iterative post-order evaluation of the recorded DAG up to (and
    including) every node in `target_nodes`. Returns the node memo
    (id(node) -> [outputs]). `seed` pre-populates the memo — the recompute
    meta-optimizer seeds checkpoint nodes with carried values so the
    segment between checkpoints re-evaluates under `jax.checkpoint`
    instead of saving residuals (SURVEY.md §2.2 P20)."""
    node_memo = dict(seed) if seed else {}
    param_values = param_values or {}

    def param_of(ref):
        v = param_values.get(id(ref.t))
        return ref.t._data if v is None else v

    def feed_of(sym):
        try:
            return feed_values[sym.feed_name]
        except KeyError:
            raise StaticGraphError(
                f"missing feed for placeholder {sym.feed_name!r}")

    for tgt in target_nodes:
        if tgt is None or id(tgt) in node_memo:
            continue
        # iterative post-order over producers — a sequential graph deeper
        # than the interpreter recursion limit must still evaluate
        stack = [tgt]
        while stack:
            n = stack[-1]
            if id(n) in node_memo:
                stack.pop()
                continue
            pending = [x.node for x in n.inputs
                       if isinstance(x, _SymArr) and x.feed_name is None
                       and x.node is not None and id(x.node) not in node_memo]
            if pending:
                stack.extend(pending)
                continue
            stack.pop()
            full = []
            for x in n.inputs:
                if isinstance(x, _SymArr):
                    full.append(feed_of(x) if x.feed_name is not None
                                else node_memo[id(x.node)][x.out_idx])
                elif isinstance(x, _ParamRef):
                    full.append(param_of(x))
                else:
                    full.append(x)
            out = n.fn(*full, **n.kwargs)
            node_memo[id(n)] = list(out) if isinstance(out, (tuple, list)) \
                else [out]
    return node_memo


def _evaluate(fetch_syms, feed_values, param_values=None, seed=None):
    """Evaluate the DAG for the given fetches. feed_values: name->array;
    param_values (optional): id(param Tensor) -> traced array, promoting
    captured parameters from closure constants to function inputs (the
    training path differentiates through this). Memoized over nodes; runs
    under whatever trace calls it (Executor jits it)."""
    for s in fetch_syms:
        if s.feed_name is None and s.node is None:
            raise StaticGraphError("symbolic value with no producer")
    memo = _run_dag(
        [s.node for s in fetch_syms if s.feed_name is None],
        feed_values, param_values, seed)
    out = []
    for s in fetch_syms:
        if s.feed_name is not None:
            try:
                out.append(feed_values[s.feed_name])
            except KeyError:
                raise StaticGraphError(
                    f"missing feed for placeholder {s.feed_name!r}")
        else:
            out.append(memo[id(s.node)][s.out_idx])
    return out


def _topo_positions(root_node):
    """id(node) -> dense post-order index for every node reachable from
    `root_node` (dependencies before dependents)."""
    order, stack = {}, [root_node]
    while stack:
        n = stack[-1]
        if id(n) in order:
            stack.pop()
            continue
        pending = [x.node for x in n.inputs
                   if isinstance(x, _SymArr) and x.node is not None
                   and id(x.node) not in order]
        if pending:
            stack.extend(pending)
            continue
        stack.pop()
        order[id(n)] = len(order)
    return order


def _collect_params(syms):
    """Deterministic post-order walk of the DAG under `syms`, returning the
    unique trainable Parameter tensors captured as _ParamRef inputs (the
    static analog of the dygraph parameter_list)."""
    seen_nodes, params, seen_p = set(), [], set()
    stack = [s.node for s in syms if s.node is not None]
    while stack:
        n = stack.pop()
        if id(n) in seen_nodes:
            continue
        seen_nodes.add(id(n))
        for x in n.inputs:
            if isinstance(x, _ParamRef):
                if id(x.t) not in seen_p:
                    seen_p.add(id(x.t))
                    params.append(x.t)
            elif isinstance(x, _SymArr) and x.node is not None:
                stack.append(x.node)
    return params


import collections as _collections

# id(jax array) -> (array, digest): the stored array pins the id so a hit
# is identity-verified (stale ids from GC'd arrays recompute); bounded LRU
_digest_memo = _collections.OrderedDict()
_DIGEST_MEMO_SIZE = 512


def _content_digest(x):
    import hashlib

    if isinstance(x, jax.Array):   # immutable: digest memoizable
        ent = _digest_memo.get(id(x))
        if ent is not None and ent[0] is x:
            _digest_memo.move_to_end(id(x))
            return ent[1]
        d = hashlib.sha1(np.asarray(x).tobytes()).hexdigest()[:16]
        # identity-verified LRU of concrete arrays only — jax.Array check
        # above guarantees no tracer reaches this store
        _digest_memo[id(x)] = (x, d)  # noqa: PTA402
        if len(_digest_memo) > _DIGEST_MEMO_SIZE:
            _digest_memo.popitem(last=False)
        return d
    # np arrays are mutable — hash fresh every time
    return hashlib.sha1(np.asarray(x).tobytes()).hexdigest()[:16]


def _describe_value(x, params_pos, pins):
    """Stable structural descriptor of a non-symbolic node input or
    closure cell. Constant ARRAY CONTENT is part of the program identity
    (two graphs differing only in a baked-in constant must not share a
    compiled executable), so arrays hash by content. Objects described by
    id are appended to `pins` — the cache entry holds them alive so a
    recycled id can never alias a dead object's descriptor."""
    if isinstance(x, _ParamRef):
        return ("param", params_pos[id(x.t)], tuple(x.t._data.shape),
                str(x.t._data.dtype))
    if isinstance(x, (bool, int, float, str, bytes, type(None))):
        return ("py", x)
    if isinstance(x, (tuple, list)):
        return (type(x).__name__,) + tuple(
            _describe_value(v, params_pos, pins) for v in x)
    if isinstance(x, Tensor):
        if isinstance(x._data, _SymArr):
            pins.append(x)
            return ("obj", "SymTensor", id(x))
        return _describe_value(x._data, params_pos, pins)
    if isinstance(x, (np.ndarray, jax.Array)):
        return ("arr", tuple(x.shape), str(x.dtype), _content_digest(x))
    pins.append(x)
    return ("obj", type(x).__name__, id(x))


def _program_signature(syms):
    """One deterministic walk over the fetched subgraph returning
    (structural key, params, pins): nodes keyed by op_name + fn code
    identity + closure/kwarg/const content + input wiring — so a REBUILT
    structurally identical program maps to the same compiled executable
    (VERDICT r3 item 8), while any difference in wiring, shapes, or
    constant content produces a different key. `pins` are the objects
    whose ids appear in the key; the cache entry must hold them alive."""
    node_order = {}     # id(node) -> dense index in reverse-topo order
    nodes = []
    params, params_pos = [], {}
    pins = []

    def visit(n):
        if id(n) in node_order:
            return
        stack = [n]
        while stack:
            cur = stack[-1]
            if id(cur) in node_order:
                stack.pop()
                continue
            pending = [x.node for x in cur.inputs
                       if isinstance(x, _SymArr) and x.node is not None
                       and id(x.node) not in node_order]
            if pending:
                stack.extend(pending)
                continue
            stack.pop()
            for x in cur.inputs:
                if isinstance(x, _ParamRef) and id(x.t) not in params_pos:
                    params_pos[id(x.t)] = len(params)
                    params.append(x.t)
            node_order[id(cur)] = len(nodes)
            nodes.append(cur)

    for s in syms:
        if isinstance(s, _GradSym):
            if s.loss_sym.node is not None:
                visit(s.loss_sym.node)
        elif s.node is not None:
            visit(s.node)

    def describe_input(x):
        if isinstance(x, _SymArr):
            if x.feed_name is not None:
                return ("feed", x.feed_name)
            return ("sym", node_order[id(x.node)], x.out_idx)
        return _describe_value(x, params_pos, pins)

    node_keys = []
    for n in nodes:
        fn = n.fn
        code = getattr(fn, "__code__", None)
        # a lambda's code object is pinned for the life of the defining
        # module/function (co_consts), so id(code) is stable across
        # rebuilds; pin it anyway for custom callables
        fn_key = (id(code) if code is not None else id(fn))
        pins.append(code if code is not None else fn)
        cells = getattr(fn, "__closure__", None) or ()
        cell_key = tuple(_describe_value(c.cell_contents, params_pos, pins)
                         for c in cells)
        kw_key = tuple((k, _describe_value(v, params_pos, pins))
                       for k, v in sorted(n.kwargs.items()))
        node_keys.append((n.op_name, fn_key, cell_key, kw_key, n.n_out,
                          tuple(describe_input(x) for x in n.inputs)))

    def describe_fetch(s):
        if isinstance(s, _GradSym):
            return ("grad", node_order[id(s.loss_sym.node)],
                    s.loss_sym.out_idx, params_pos.get(id(s.param), -1))
        if s.feed_name is not None:
            return ("feed", s.feed_name)
        return ("sym", node_order[id(s.node)], s.out_idx)

    key = (tuple(node_keys), tuple(describe_fetch(s) for s in syms))
    return key, params, pins


def _owning_program(syms):
    """The Program whose placeholders feed this DAG (so minimize attaches
    the train op to the program the loss was RECORDED under, not whatever
    program guard is active at minimize() time)."""
    seen = set()
    stack = [s.node for s in syms if s.node is not None]
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        for x in n.inputs:
            if isinstance(x, _SymArr):
                if x.feed_name is not None and x.program is not None:
                    return x.program
                if x.node is not None:
                    stack.append(x.node)
    return _state["main"]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """ref static.append_backward: register the loss's backward on the
    main program and return [(param, grad)] pairs. The grad entries are
    fetchable symbolic Tensors — Executor.run computes them with ONE
    jax.value_and_grad over the recorded DAG (shared with the forward
    fetches). parameter_list restricts to the given params; no_grad_set
    (param tensors or their names) excludes params from training."""
    if not _is_sym(loss):
        raise StaticGraphError("append_backward expects a static loss Tensor")
    if tuple(loss._data.aval.shape) not in ((), (1,)):
        raise StaticGraphError(
            f"append_backward: loss must be a scalar, got shape "
            f"{loss._data.aval.shape}")
    params = list(parameter_list) if parameter_list \
        else _collect_params([loss._data])
    if no_grad_set:
        frozen_ids = {id(t) for t in no_grad_set if isinstance(t, Tensor)}
        frozen_names = {t for t in no_grad_set if isinstance(t, str)}
        params = [p for p in params
                  if id(p) not in frozen_ids
                  and (p.name or "") not in frozen_names]
        if not params:
            raise StaticGraphError(
                "append_backward: no_grad_set excludes every parameter")
    pairs = []
    for p in params:
        g = Tensor.__new__(Tensor)
        g._data = _GradSym(jax.ShapeDtypeStruct(p._data.shape, p._data.dtype),
                           loss_sym=loss._data, param=p)
        g.grad = None
        g.stop_gradient = True
        g._tape_node = None
        g.name = None
        g.persistable = False
        g.trainable = False
        pairs.append((p, g))
    return pairs


class _GradSym(_SymArr):
    """d(loss)/d(param) over the recorded DAG — resolvable only by
    Executor.run (which batches all grads into one value_and_grad)."""

    __slots__ = ("loss_sym", "param")

    def __init__(self, aval, loss_sym=None, param=None):
        super().__init__(aval)
        self.loss_sym = loss_sym
        self.param = param


def register_minimize(optimizer, loss, parameters=None, no_grad_set=None):
    """Optimizer.minimize under static mode: remember (loss, optimizer) on
    the program the loss was recorded under; Executor.run applies the
    update whenever it runs that program. Returns (None, params_grads)
    per the reference API."""
    if not _is_sym(loss):
        raise StaticGraphError("minimize expects a static loss Tensor")
    pairs = append_backward(loss, parameter_list=parameters,
                            no_grad_set=no_grad_set)
    params = [p for p, _ in pairs]
    if not params:
        raise StaticGraphError(
            "minimize: no trainable parameters reachable from the loss "
            "(were layers built under paddle.enable_static()?)")
    if optimizer._parameter_list is None:
        optimizer._parameter_list = params
        for i, p in enumerate(params):
            optimizer._param_names[id(p)] = p.name or f"param_{i}"
    _owning_program([loss._data])._train_op = (loss, optimizer)
    return None, pairs


def _mp_state_shardings(params, mesh, opt, gm_k):
    """Per-param/state shardings for static hybrid training. With an mp
    axis (>1), params whose last dim divides mp shard over it (column
    policy; the reference's tensor_parallel_optimizer reaches the same
    layouts through per-layer program rewrites — fleet/meta_optimizers/
    (U)); optimizer-state leaves mirror their param. With a 'sharding'
    axis (>1), optimizer-state leaves additionally shard their FIRST dim
    over it (static ZeRO-1 — the static sharding_optimizer (U)): params
    stay replicated, GSPMD reduce-scatters grads into the sharded update
    and all-gathers the new params. Scalars replicate."""
    from jax.sharding import NamedSharding, PartitionSpec

    repl = NamedSharding(mesh, PartitionSpec())
    mp = dict(mesh.shape).get("mp", 1)
    zr = dict(mesh.shape).get("sharding", 1)
    param_sh = []
    for p in params:
        nd = p._data.ndim
        if mp > 1 and nd >= 2 and p._data.shape[-1] % mp == 0:
            param_sh.append(NamedSharding(
                mesh, PartitionSpec(*([None] * (nd - 1) + ["mp"]))))
        else:
            param_sh.append(repl)

    def state_leaf_sh(a, p_sh, p):
        if getattr(a, "shape", None) is None \
                or tuple(a.shape) != tuple(p._data.shape):
            return repl
        spec = list(p_sh.spec) + [None] * (len(a.shape) - len(p_sh.spec))
        if zr > 1 and len(a.shape) >= 1 and a.shape[0] % zr == 0 \
                and spec[0] is None:
            spec[0] = "sharding"
        while spec and spec[-1] is None:
            spec.pop()
        return NamedSharding(mesh, PartitionSpec(*spec))

    opt_sh = [
        jax.tree.map(lambda a, _s=s, _p=p: state_leaf_sh(a, _s, _p),
                     opt._accumulators[id(p)])
        if opt is not None else []
        for p, s in zip(params, param_sh)]
    acc_sh = list(param_sh) if gm_k > 1 else []
    return param_sh, opt_sh, acc_sh


def _dp_local_count(mesh):
    """Number of distinct DP-axis coordinates this process owns in a
    (possibly hybrid) mesh. A process's batch shard splits over the dp
    axis ONLY — counting all its devices would demand the wrong divisor
    on a dp×mp mesh (advisor r4)."""
    dp_ax = list(mesh.axis_names).index("dp")
    by_dp = np.moveaxis(mesh.devices, dp_ax, 0)
    return max(1, sum(
        1 for i in range(by_dp.shape[0])
        if any(d.process_index == jax.process_index()
               for d in np.atleast_1d(by_dp[i]).flat)))


def _dp_global(a, mesh, n_devices, spec):
    """Assemble a host-local value into a global array over `mesh` with
    `spec` (multi-process static-dp); pass through values that are
    already global on all of the mesh's devices."""
    if isinstance(a, jax.Array) and len(a.devices()) == n_devices:
        return a
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        np.asarray(a), mesh, spec)


def _scaler_next(state, finite, cfg):
    """Dynamic loss-scale bookkeeping (ref OptimizerWithMixedPrecision /
    update_loss_scaling op semantics): grow the scale after
    `incr_every_n_steps` consecutive finite steps, shrink it after
    `decr_every_n_nan_or_inf` consecutive non-finite steps."""
    if not cfg.get("use_dynamic_loss_scaling", True):
        return state
    found = ~finite
    good = jnp.where(found, 0, state["good"] + 1)
    bad = jnp.where(found, state["bad"] + 1, 0)
    grow = good >= int(cfg.get("incr_every_n_steps", 1000))
    shrink = bad >= int(cfg.get("decr_every_n_nan_or_inf", 2))
    scale = jnp.where(
        shrink, state["scale"] * float(cfg.get("decr_ratio", 0.5)),
        jnp.where(grow, state["scale"] * float(cfg.get("incr_ratio", 2.0)),
                  state["scale"]))
    return {"scale": scale,
            "good": jnp.where(grow, 0, good),
            "bad": jnp.where(shrink, 0, bad)}


class Executor:
    """ref static.Executor: compiles + runs the fetched subgraph as ONE
    XLA program per (graph structure, feed shapes) signature — the key is
    a STRUCTURAL hash (VERDICT r3 item 8), so rebuilding an equivalent
    program (e.g. per serving request) hits the cache instead of re-
    jitting, and the cache is LRU-bounded so a long-lived executor does
    not pin every program it ever ran. When the program carries a train
    op (Optimizer.minimize) or the fetches include append_backward grads,
    the compiled program is jax.value_and_grad through the DAG with the
    parameters promoted to traced (and updated) inputs."""

    CACHE_SIZE = 64

    def __init__(self, place=None):
        self.place = place
        self._cache = _collections.OrderedDict()
        self._ck_cache = _collections.OrderedDict()
        # identity front cache: same live fetch-tensor objects -> skip the
        # O(nodes) signature walk on the hot serving path (fetch identity
        # implies graph identity while the syms — pinned here — are alive)
        self._front = _collections.OrderedDict()

    def _cache_get(self, key):
        ent = self._cache.get(key)
        if ent is not None:
            self._cache.move_to_end(key)
            return ent[0]
        return None

    def _cache_put(self, key, fn, pins=()):
        self._cache[key] = (fn, pins)
        if len(self._cache) > self.CACHE_SIZE:
            self._cache.popitem(last=False)
        return fn

    def _signature(self, syms):
        fkey = tuple(id(s) for s in syms)
        ent = self._front.get(fkey)
        if ent is not None and all(a is b for a, b in zip(ent[0], syms)):
            self._front.move_to_end(fkey)
            return ent[1], ent[2], ent[3]
        struct_key, params, pins = _program_signature(syms)
        self._front[fkey] = (list(syms), struct_key, params, pins)
        if len(self._front) > self.CACHE_SIZE:
            self._front.popitem(last=False)
        return struct_key, params, pins

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        prog = program if program is not None else _state["main"]
        feed = feed or {}
        fetch_list = fetch_list or []
        syms = []
        for f in fetch_list:
            if not _is_sym(f):
                raise StaticGraphError(
                    "fetch_list entries must be static-program Tensors")
            syms.append(f._data)
        feed_names = sorted(feed)
        # feeds stay HOST arrays until the compiled program consumes them
        # (jit transfers per its in_shardings) — committing to a device
        # here would force multi-process dp to round-trip them back
        # through the host for global assembly
        feed_arrays = [np.asarray(feed[k]) for k in feed_names]
        train_op = getattr(prog, "_train_op", None)
        grad_syms = [s for s in syms if isinstance(s, _GradSym)]
        if train_op is not None or grad_syms:
            return self._run_train(prog, train_op, syms, grad_syms,
                                   feed_names, feed_arrays, return_numpy)
        # one walk computes the structural key AND the current program's
        # params (the cache may hold an executable traced from a DIFFERENT
        # but structurally identical program — its param/feed wiring is
        # positional, so the current params ride in by position)
        struct_key, params, pins = self._signature(syms)
        key = (struct_key, tuple(feed_names),
               tuple((a.shape, str(a.dtype)) for a in feed_arrays))
        fn = self._cache_get(key)
        if fn is None:
            # parameters enter as traced inputs (not closure constants) so
            # a cached executable always sees their CURRENT values —
            # required once minimize() updates them between runs
            def eval_fn(param_arrays, *arrays):
                vals = dict(zip(feed_names, arrays))
                pv = {id(p): a for p, a in zip(params, param_arrays)}
                return tuple(_evaluate(syms, vals, pv))

            fn = self._cache_put(key, jax.jit(eval_fn), pins)
        outs = fn([p._data for p in params], *feed_arrays)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _run_train(self, prog, train_op, syms, grad_syms, feed_names,
                   feed_arrays, return_numpy):
        """One optimizer step (and/or grad computation) over the recorded
        DAG: ONE compiled program runs forward, backward and update.

        Static meta-optimizer hooks (SURVEY.md §2.2 P20; set by
        fleet.StaticMetaOptimizer.minimize):
        - `prog._recompute_checkpoints`: list of _SymArr — the backward
          rematerializes each inter-checkpoint segment (`jax.checkpoint`)
          instead of saving its residuals.
        - `opt._static_amp_scaler`: fp16 dynamic loss scaling — loss is
          scaled inside the compiled program, grads unscaled, non-finite
          steps skip the update and shrink the scale.
        - `opt._gm_k` / `opt._gm_avg`: gradient merge — grads accumulate
          across k runs; the update applies on every k-th.
        """
        if train_op is not None:
            loss_t, opt = train_op
            loss_sym = loss_t._data
        else:
            opt = None
            loss_sym = grad_syms[0].loss_sym
        for g in grad_syms:
            if g.loss_sym is not loss_sym:
                raise StaticGraphError(
                    "fetching gradients of two different losses in one "
                    "run is not supported")
        params = (list(opt._parameter_list) if opt is not None
                  else _collect_params([loss_sym]))
        if opt is not None:
            for p in params:
                opt._state_for(p)
        fwd_syms = [s for s in syms if not isinstance(s, _GradSym)]

        # ---- meta-optimizer configuration (defaults = plain training) ----
        ck_syms = list(getattr(prog, "_recompute_checkpoints", ()) or ())
        ck_nodes = []
        if ck_syms and loss_sym.node is not None:
            # memoized per (program, loss, checkpoint set): the O(nodes)
            # topo walk must not run on every step of a cached train loop
            ck_key = (id(prog), id(loss_sym),
                      tuple(id(s) for s in ck_syms))
            ent = self._ck_cache.get(ck_key)
            if ent is not None:
                ck_nodes = ent[0]
            else:
                order = _topo_positions(loss_sym.node)
                seen_ck = set()
                for s in ck_syms:
                    if s.feed_name is not None:
                        continue  # feeds are always live — nothing to save
                    if s.node is None or id(s.node) not in order:
                        raise StaticGraphError(
                            "recompute checkpoint is not reachable from "
                            "the loss of this program")
                    if id(s.node) not in seen_ck:
                        seen_ck.add(id(s.node))
                        ck_nodes.append(s.node)
                ck_nodes.sort(key=lambda n: order[id(n)])
                # pin the keyed objects so a recycled id can't alias
                self._ck_cache[ck_key] = (ck_nodes,
                                          (prog, loss_sym, ck_syms))
                if len(self._ck_cache) > self.CACHE_SIZE:
                    self._ck_cache.popitem(last=False)
        scaler = (getattr(opt, "_static_amp_scaler", None)
                  if opt is not None else None)
        dp_mesh = (getattr(opt, "_static_dp_mesh", None)
                   if opt is not None else None)
        dp_batch_like, dp_multi, _dp_nd = None, False, 0
        if dp_mesh is not None:
            dp = int(dp_mesh.shape["dp"])
            _dp_nd = dp_mesh.devices.size
            dp_batch_like = []
            for name, a in zip(feed_names, feed_arrays):
                ph = prog.placeholders.get(name)
                orig = getattr(getattr(ph, "_data", None),
                               "orig_shape", None)
                # only BATCH feeds shard over dp — identified by a
                # dynamic (None/-1) declared leading dim; fixed-shape
                # auxiliaries (class weights, masks) replicate
                dp_batch_like.append(
                    a.ndim >= 1 and orig is not None
                    and len(orig) >= 1 and orig[0] is None)
            dp_multi = any(d.process_index != jax.process_index()
                           for d in dp_mesh.devices.flat)
            if dp_multi:
                # multi-process: each trainer feeds ITS OWN batch shard
                # (the reference's per-trainer dp feeding); assemble the
                # global arrays the SPMD program consumes
                from jax.sharding import PartitionSpec as _PS

                local_n = _dp_local_count(dp_mesh)
                for name, a, bl in zip(feed_names, feed_arrays,
                                       dp_batch_like):
                    if bl and a.shape[0] % local_n:
                        raise StaticGraphError(
                            f"static dp training: this process's batch "
                            f"shard for feed {name!r} has leading dim "
                            f"{a.shape[0]}, not divisible by its "
                            f"{local_n} local dp devices")
                feed_arrays = [
                    _dp_global(a, dp_mesh, _dp_nd,
                               _PS("dp") if bl else _PS())
                    for a, bl in zip(feed_arrays, dp_batch_like)]
            else:
                for name, a, bl in zip(feed_names, feed_arrays,
                                       dp_batch_like):
                    if bl and a.shape[0] % dp:
                        raise StaticGraphError(
                            f"static dp training: batch feed {name!r} "
                            f"leading dim {a.shape[0]} is not divisible "
                            f"by dp={dp}")
        gm_k = int(getattr(opt, "_gm_k", 1) or 1) if opt is not None else 1
        gm_avg = bool(getattr(opt, "_gm_avg", True))
        if gm_k > 1:
            if getattr(opt, "_gm_buffers", None) is None:
                opt._gm_buffers = [jnp.zeros_like(p._data) for p in params]
                # with fp16 scaling, non-finite micro-steps don't
                # accumulate — the merged average divides by the number
                # of steps that actually landed, not by k
                opt._gm_nacc = jnp.zeros((), jnp.int32)
                opt._gm_count = 0
            apply_update = (opt._gm_count + 1) % gm_k == 0
        else:
            apply_update = True

        # the train executable is bound to the optimizer object (its
        # accumulators key on these exact param tensors), so identity —
        # not structure — is the right key here; every meta config baked
        # into the closure (gm_avg, scaler thresholds) must also key it,
        # or re-minimizing with changed configs would reuse stale code
        scaler_key = (tuple(sorted((k, str(v))
                                   for k, v in scaler["cfg"].items()))
                      if scaler is not None else None)
        key = ("train", id(prog), id(loss_sym), id(opt), apply_update,
               gm_k, gm_avg, scaler_key,
               id(dp_mesh) if dp_mesh is not None else None,
               tuple(id(n) for n in ck_nodes),
               tuple(id(s) for s in syms), tuple(feed_names),
               tuple((a.shape, str(a.dtype)) for a in feed_arrays))
        cached = self._cache_get(key)
        if cached is None:
            def train_fn(param_arrays, opt_states, lr, scaler_state, acc,
                         nacc, *arrays):
                vals = dict(zip(feed_names, arrays))
                scale = scaler_state.get("scale")

                def loss_and_fetches(pas):
                    pv = {id(p): a for p, a in zip(params, pas)}
                    seed = {}
                    if ck_nodes:
                        # recompute: evaluate each checkpoint's node under
                        # jax.checkpoint from (params, feeds, earlier
                        # checkpoints) — only checkpoint values are saved
                        # for the backward; segment internals rematerialize
                        seeded_ids = []
                        for n in ck_nodes:
                            prev_vals = [seed[i] for i in seeded_ids]

                            def seg(pas_, prev_, _n=n,
                                    _ids=tuple(seeded_ids)):
                                pv_ = {id(p): a
                                       for p, a in zip(params, pas_)}
                                sm = dict(zip(_ids, prev_))
                                memo = _run_dag([_n], vals, pv_, seed=sm)
                                return memo[id(_n)]

                            seed[id(n)] = jax.checkpoint(seg)(pas, prev_vals)
                            seeded_ids.append(id(n))
                    outs = _evaluate([loss_sym] + fwd_syms, vals, pv,
                                     seed=seed or None)
                    loss = outs[0]
                    if scale is not None:
                        loss = loss * scale.astype(loss.dtype)
                    return loss, outs[1:]

                (_, fwd_vals), grads = jax.value_and_grad(
                    loss_and_fetches, has_aux=True)(tuple(param_arrays))
                finite = jnp.asarray(True)
                new_scaler_state = scaler_state
                if scale is not None:
                    inv = 1.0 / scale
                    grads = tuple(
                        (g.astype(jnp.float32) * inv).astype(g.dtype)
                        for g in grads)
                    for g in grads:
                        finite &= jnp.all(
                            jnp.isfinite(g.astype(jnp.float32)))
                    new_scaler_state = _scaler_next(
                        scaler_state, finite, scaler["cfg"])
                if gm_k > 1:
                    safe = [jnp.where(finite, g, jnp.zeros_like(g))
                            for g in grads] if scale is not None else grads
                    new_acc = [a + g for a, g in zip(acc, safe)]
                    new_nacc = nacc + jnp.where(finite, 1, 0).astype(
                        jnp.int32)
                else:
                    new_acc, new_nacc = acc, nacc
                if opt is None or not apply_update:
                    return (fwd_vals, grads, param_arrays, opt_states,
                            new_scaler_state, new_acc, new_nacc)
                from ..core.tensor import Tensor as _T

                if gm_k > 1:
                    denom = (jnp.maximum(new_nacc, 1).astype(jnp.float32)
                             if gm_avg else jnp.asarray(1.0, jnp.float32))
                    eff = [a / denom.astype(a.dtype) for a in new_acc]
                    out_acc = [jnp.zeros_like(a) for a in new_acc]
                    out_nacc = jnp.zeros((), jnp.int32)
                else:
                    eff = list(grads)
                    out_acc, out_nacc = new_acc, new_nacc
                pairs = [(p, _T(g)) for p, g in zip(params, eff)]
                if opt._grad_clip is not None:
                    pairs = opt._grad_clip(pairs)
                g_by_id = {id(p): g._data for p, g in pairs}
                new_params, new_states = [], []
                for p, a, st in zip(params, param_arrays, opt_states):
                    g_arr = opt._regularized_grad(
                        p, g_by_id[id(p)].astype(a.dtype))
                    plr = lr * getattr(p, "optimize_attr",
                                       {}).get("learning_rate", 1.0)
                    np_, nst = opt._update_for(p, a, g_arr, st, plr)
                    new_params.append(np_)
                    new_states.append(nst)
                if scale is not None:
                    # a non-finite step must not touch params or optimizer
                    # state (reference skip-update semantics): for gm_k==1
                    # that's THIS step's finiteness; for merge, skip only
                    # if NO micro-step accumulated anything
                    keep = finite if gm_k == 1 else new_nacc > 0
                    new_params = [jnp.where(keep, n, o) for n, o
                                  in zip(new_params, param_arrays)]
                    new_states = jax.tree.map(
                        lambda n, o: jnp.where(keep, n, o),
                        new_states, opt_states)
                return (fwd_vals, grads, new_params, new_states,
                        new_scaler_state, out_acc, out_nacc)

            if dp_mesh is not None:
                # static DATA-PARALLEL training: feeds shard over the dp
                # axis — GSPMD inserts the gradient all-reduce the
                # reference's transpiled program carried as explicit
                # c_allreduce ops. Static TENSOR-PARALLEL (r5, the static
                # analog of the reference's tensor_parallel_optimizer
                # fleet/meta_optimizers/ (U)): when the mesh has an mp
                # axis, every recorded param whose last dim divides mp
                # shards over it (column policy — the reference reaches
                # the same layout through per-layer annotations; GSPMD
                # places the matching collectives), optimizer state
                # mirrors its param, and the state outputs pin to the
                # entry shardings so updates stay sharded step to step.
                from jax.sharding import NamedSharding, PartitionSpec

                repl = NamedSharding(dp_mesh, PartitionSpec())
                param_sh, opt_sh, acc_sh = _mp_state_shardings(
                    params, dp_mesh, opt, gm_k)
                feed_sh = [
                    NamedSharding(dp_mesh, PartitionSpec("dp")) if bl
                    else repl for bl in dp_batch_like]
                # arg order: params, opt_states, lr, scaler_state, acc,
                # nacc, *feeds; outputs: (fwd_vals, grads, new_params,
                # new_states, new_scaler_state, out_acc, out_nacc)
                cached = self._cache_put(key, jax.jit(
                    train_fn,
                    in_shardings=(param_sh, opt_sh, repl, repl, acc_sh,
                                  repl) + tuple(feed_sh),
                    out_shardings=(repl, tuple(param_sh), param_sh,
                                   opt_sh, repl, acc_sh, repl)))
            else:
                cached = self._cache_put(key, jax.jit(train_fn))
        param_arrays = [p._data for p in params]
        opt_states = ([opt._accumulators[id(p)] for p in params]
                      if opt is not None else [])
        lr = (jnp.asarray(opt.get_lr(), jnp.float32) if opt is not None
              else jnp.zeros((), jnp.float32))
        scaler_state = dict(scaler["state"]) if scaler is not None else {}
        acc = list(opt._gm_buffers) if gm_k > 1 else []
        nacc = (opt._gm_nacc if gm_k > 1
                else jnp.zeros((), jnp.int32))
        if dp_multi:
            # first call: per-process state arrays (identical across
            # processes by seeded construction) become global replicated
            # arrays; later calls see the jit outputs, already global.
            # The converted arrays are STASHED BACK so non-apply
            # gradient-merge micro-steps don't re-round-trip the whole
            # model through host memory every step.
            from jax.sharding import PartitionSpec as _PS

            def g(a):
                return _dp_global(a, dp_mesh, _dp_nd, _PS())

            param_arrays = [g(a) for a in param_arrays]
            opt_states = jax.tree.map(g, opt_states)
            lr = g(lr)
            scaler_state = jax.tree.map(g, scaler_state)
            acc = [g(a) for a in acc]
            nacc = g(nacc)
            if (dict(dp_mesh.shape).get("mp", 1) > 1
                    or dict(dp_mesh.shape).get("sharding", 1) > 1) \
                    and not getattr(opt, "_static_mp_placed", False):
                # static-mp: the replicated global arrays move to their
                # mp shardings ONCE (committed arrays can't be resharded
                # by in_shardings); later calls see the jit outputs,
                # already sharded — the flag skips the per-step
                # sharding-object rebuild
                opt._static_mp_placed = True
                p_sh, o_sh, a_sh = _mp_state_shardings(
                    params, dp_mesh, opt, gm_k)
                param_arrays = [
                    a if a.sharding == s else jax.device_put(a, s)
                    for a, s in zip(param_arrays, p_sh)]
                opt_states = [
                    jax.tree.map(
                        lambda a, s: a if a.sharding == s
                        else jax.device_put(a, s), st, sh)
                    for st, sh in zip(opt_states, o_sh)]
                acc = [a if a.sharding == s else jax.device_put(a, s)
                       for a, s in zip(acc, a_sh)]
            for p, ga in zip(params, param_arrays):
                p._data = ga
            if opt is not None:
                for p, st in zip(params, opt_states):
                    opt._accumulators[id(p)] = st
                if gm_k > 1:
                    opt._gm_buffers = list(acc)
                    opt._gm_nacc = nacc
            if scaler is not None:
                scaler["state"] = dict(scaler_state)
        (fwd_vals, grads, new_params, new_states, new_scaler_state,
         new_acc, new_nacc) = cached(param_arrays, opt_states, lr,
                                     scaler_state, acc, nacc, *feed_arrays)
        if scaler is not None:
            scaler["state"] = dict(new_scaler_state)
        if gm_k > 1:
            opt._gm_buffers = list(new_acc)
            opt._gm_nacc = new_nacc
            opt._gm_count += 1
        if opt is not None and apply_update:
            for p, arr in zip(params, new_params):
                p._data = arr
            for p, st in zip(params, new_states):
                opt._accumulators[id(p)] = st
            opt._step_count += 1
        grad_by_pid = {id(p): g for p, g in zip(params, grads)}
        outs, fi = [], 0
        for s in syms:
            if isinstance(s, _GradSym):
                try:
                    outs.append(grad_by_pid[id(s.param)])
                except KeyError:
                    raise StaticGraphError(
                        "fetched grad is for a parameter not reachable "
                        "from the loss")
            else:
                outs.append(fwd_vals[fi])
                fi += 1
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """ref static.save_inference_model: export the fetched subgraph as the
    same StableHLO artifact jit.save writes — loadable by jit.load AND
    servable by paddle.inference.create_predictor."""
    import os
    import pickle

    if not isinstance(feed_vars, (list, tuple)):
        feed_vars = [feed_vars]
    if not isinstance(fetch_vars, (list, tuple)):
        fetch_vars = [fetch_vars]
    names = []
    for v in feed_vars:
        if not (_is_sym(v) and v._data.feed_name):
            raise StaticGraphError("feed_vars must be static.data placeholders")
        names.append(v._data.feed_name)
    syms = [v._data for v in fetch_vars]

    def infer_fn(state_arrays, *arg_arrays):
        del state_arrays  # graph constants ride in the closure
        vals = dict(zip(names, arg_arrays))
        return tuple(_evaluate(syms, vals))

    # dynamic (None/-1) placeholder dims export as SYMBOLIC dims so the
    # served program accepts any size there (batch polymorphism) — shared
    # helper with jit.save (independent symbols, shared-per-axis retry)
    from ..jit.api import export_with_dynamic_dims, write_artifact

    spec_shapes = []
    specs = []
    for v in feed_vars:
        orig = v._data.orig_shape or v._data.aval.shape
        specs.append((tuple(orig), v._data.aval.dtype))
        spec_shapes.append([None if d is None else int(d) for d in orig])
    exported = export_with_dynamic_dims(jax.jit(infer_fn), [[]], specs)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    from ..framework.io import save as fsave

    fsave({}, path_prefix + ".pdiparams")
    out_names, used = [], set()
    for i, v in enumerate(fetch_vars):
        base = getattr(v, "name", None) or f"output_{i}"
        n, k = base, 0
        while n in used:                  # names must be unique handles
            k += 1
            n = f"{base}_{k}"
        used.add(n)
        out_names.append(n)
    write_artifact(
        path_prefix, exported,
        [(shape, str(np.dtype(v._data.aval.dtype)))
         for shape, v in zip(spec_shapes, feed_vars)],
        names, [], output_names=out_names)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """ref static.load_inference_model -> (program, feed_names,
    fetch_targets); here the 'program' is the loaded TranslatedLayer."""
    from ..jit.api import load as jit_load

    layer = jit_load(path_prefix)
    return layer, list(getattr(layer, "_input_names", [])), layer
