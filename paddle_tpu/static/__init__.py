"""paddle.static — a REAL minimal static-graph mode (ref: ProgramDesc +
Executor/InterpreterCore, SURVEY.md §2.1 N10/N11).

TPU-native stance, upgraded in r3: instead of rebuilding a ProgramDesc
interpreter, static mode makes the op dispatch LAZY — `static.data`
placeholders are symbolic, ops touching them record graph nodes (out shapes
via jax abstract eval, the InferMeta analog), and `Executor.run` compiles
the fetched subgraph as ONE `jax.jit` program of the feeds: build / run /
save_inference_model (StableHLO, servable by paddle.inference) /
load_inference_model. Static-mode TRAINING (r4): `append_backward` and
`Optimizer.minimize` differentiate the recorded DAG with jax.value_and_grad
(parameters promoted from closure constants to traced inputs) and apply the
optimizer's functional update inside the same compiled program — the
reference's `exe.run(startup); exe.run(main, feed, [loss])` loop trains.
Static meta-optimizers (P20, r4): `fleet.distributed_optimizer` under
static mode returns a program-rewriting wrapper (amp cast rewrite + fp16
dynamic loss scaling, recompute over declared checkpoints, k-step gradient
merge, Lamb swap — fleet/meta_optimizers/static_meta_optimizer.py). The
serious training path remains dygraph + `paddle_tpu.jit.TrainStep`
(SURVEY.md §7).
"""

from ..jit.api import InputSpec
from ..nn import Layer  # re-export convenience

from .graph import (
    Executor,
    Program,
    StaticGraphError,
    append_backward,
    data,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    in_static_mode,
    load_inference_model,
    program_guard,
    save_inference_model,
)

from . import nn  # noqa: E402
from . import amp  # noqa: E402


def name_scope(prefix=None):
    import contextlib

    return contextlib.nullcontext()


__all__ = [
    "InputSpec", "Layer", "Executor", "Program", "StaticGraphError",
    "append_backward",
    "data", "default_main_program", "default_startup_program",
    "disable_static", "enable_static", "in_static_mode",
    "load_inference_model", "program_guard", "save_inference_model", "nn",
    "name_scope", "amp",
]
