"""paddle.static compatibility shim.

The reference's static graph stack (ProgramDesc/Executor/InterpreterCore —
SURVEY.md §2.1 N10/N11) is deliberately NOT rebuilt: under XLA the compiled
program IS the static graph, produced by tracing (`paddle_tpu.jit.to_static`).
This module keeps the commonly-used entry points alive, mapping them to their
trace-based equivalents, and raises informative errors for the legacy
Program-construction API.
"""

from ..jit.api import InputSpec
from ..nn import Layer  # re-export convenience


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    raise NotImplementedError(
        "Static Program serialization is replaced by paddle_tpu.jit.save "
        "(weights + serialized StableHLO via jax.export)."
    )


def load_inference_model(path_prefix, executor=None, **kwargs):
    raise NotImplementedError("Use paddle_tpu.jit.load.")


class Program:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "Explicit Program construction does not exist on the TPU build; "
            "decorate your function with paddle_tpu.jit.to_static instead."
        )


def default_main_program():
    raise NotImplementedError("No global static program; use jit.to_static.")


def default_startup_program():
    raise NotImplementedError("No global static program; use jit.to_static.")


class Executor:
    def __init__(self, place=None):
        raise NotImplementedError(
            "The XLA runtime executes compiled programs directly; use "
            "jit.to_static / jit.TrainStep instead of Executor.run."
        )


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


def name_scope(prefix=None):
    import contextlib

    return contextlib.nullcontext()


class _ShimAttributeError(NotImplementedError, AttributeError):
    """Raised by namespace shims: informative like the sibling shims'
    NotImplementedError, but still an AttributeError so hasattr/getattr
    feature-detection (and dunder protocol lookups, e.g. deepcopy) keep
    working for code ported from the reference."""


class _StaticAmpShim:
    """paddle.static.amp shim: static-graph AMP program rewriting does not
    exist on the TPU build — dynamic `paddle_tpu.amp.auto_cast` /
    `amp.decorate` compose with `jit.to_static` (bf16 policy is applied at
    trace time, so the compiled program is already mixed-precision)."""

    def __getattr__(self, name):
        raise _ShimAttributeError(
            f"paddle.static.amp.{name} rewrites static Programs, which do not "
            "exist on the TPU build; use paddle_tpu.amp.auto_cast / "
            "amp.decorate with jit.to_static instead."
        )


amp = _StaticAmpShim()
