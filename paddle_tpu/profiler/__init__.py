"""paddle.profiler parity (ref: python/paddle/profiler/profiler.py (U) — the
Python scheduler/RecordEvent face of N20).

TPU-native backing: jax.profiler (XLA/xprof traces viewable in TensorBoard or
Perfetto) replaces the host tracer + CUPTI stack. RecordEvent maps to
jax.profiler.TraceAnnotation so user spans appear inside the device trace.
"""

from __future__ import annotations

import contextlib
import enum
import os
import time

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    total = closed + ready + record

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._export_dir = dir_name

    return handler


export_protobuf = export_chrome_tracing


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, emit_nvtx=False):
        # tuple form means "record [start, end) once" (reference contract);
        # repeat=1 — the default repeat=0 would cycle the window forever
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(closed=scheduler[0], ready=0,
                           record=scheduler[1] - scheduler[0], repeat=1)
            if isinstance(scheduler, (tuple, list)) else None
        )
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._active = False
        self._export_dir = os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
        self._step_times = []
        self._last_step_t = None

    def start(self):
        self._last_step_t = time.time()
        if not self._timer_only:
            try:
                jax.profiler.start_trace(self._export_dir)
                self._active = True
            except Exception:
                self._active = False
        return self

    def stop(self):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.time()
        if self._last_step_t is not None:
            self._step_times.append((now - self._last_step_t, num_samples))
        self._last_step_t = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        dt, ns = self._step_times[-1]
        ips = (ns / dt) if (ns and dt > 0) else (1.0 / dt if dt > 0 else 0.0)
        return f"batch_cost: {dt:.5f} s, ips: {ips:.3f} {unit or 'steps'}/s"

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        times = [t for t, _ in self._step_times]
        if not times:
            return "no steps recorded"
        import numpy as np

        if len(times) == 1:
            # a single sample has no distribution; percentile interpolation
            # over an empty tail is meaningless — report the one value as
            # every quantile instead of crashing/garbage
            t = times[0]
            p50 = p99 = t
        else:
            p50 = float(np.percentile(times, 50))
            p99 = float(np.percentile(times, 99))
        lines = [f"steps: {len(times)}  avg: {np.mean(times)*1e3:.3f} ms  "
                 f"p50: {p50*1e3:.3f} ms  p99: {p99*1e3:.3f} ms"]
        lines.extend(self._histogram_lines())
        return "\n".join(lines)

    @staticmethod
    def _histogram_lines():
        """One line per observability histogram family with data — the
        process-wide view (compile seconds, step time, span durations)
        alongside this profiler's own step timer."""
        from ..observability import metrics as _obs_metrics

        lines = []
        for name, fam in sorted(_obs_metrics.default_registry()
                                .metrics().items()):
            if not isinstance(fam, _obs_metrics.Histogram):
                continue
            for label_s, st in fam.snapshot_values().items():
                if not st.get("count"):
                    continue
                suffix = f"{{{label_s}}}" if label_s else ""
                lines.append(
                    f"  {name}{suffix}: n={st['count']} "
                    f"mean={st['mean']:.6f} p50={st['p50']:.6f} "
                    f"p95={st['p95']:.6f} p99={st['p99']:.6f}")
        return lines

    def export(self, path=None, format="json"):
        # xplane files land in self._export_dir via stop_trace
        return self._export_dir

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """User-annotated span; shows up in the xprof/TensorBoard trace."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(filename):
    raise NotImplementedError("open xprof traces with TensorBoard / Perfetto")


@contextlib.contextmanager
def profiler_guard(*a, **k):
    p = Profiler()
    p.start()
    try:
        yield p
    finally:
        p.stop()


# --------------------------------------------------------------------------
# counter registry: subsystems (paddle_tpu.serving's Engine, dataloaders,
# ...) publish live observability counters here — queue depth, TTFT,
# tokens/s, slot utilization, compile-cache hits — so one profiler-side
# call snapshots the whole process without importing every subsystem.
#
# The registry itself now lives in paddle_tpu.observability.metrics (one
# process-wide registry, same {name: zero-arg callable} contract); these
# names stay as a back-compat facade so PR 2-era callers keep working.


def register_counter_provider(name, provider):
    """Register a zero-arg callable returning a {counter: value} mapping
    under ``name`` (later registrations replace earlier ones)."""
    from ..observability import metrics as _obs_metrics

    _obs_metrics.register_provider(name, provider)


def unregister_counter_provider(name):
    from ..observability import metrics as _obs_metrics

    _obs_metrics.unregister_provider(name)


def counters():
    """Snapshot every registered provider: {name: {counter: value}}.
    A provider that raises is reported as an error string instead of
    poisoning the whole snapshot."""
    from ..observability import metrics as _obs_metrics

    return _obs_metrics.provider_counters()
