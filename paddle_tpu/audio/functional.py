"""paddle.audio.functional parity (ref: python/paddle/audio/functional/ (U):
window.py, functional.py — mel/fbank/dct math over jnp)."""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.op_call import apply
from ..tensor.creation import _as_t


# ----------------------------------------------------------------- windows

def get_window(window, win_length, fftbins=True, dtype="float64"):
    """ref window.py get_window: 'hamming', 'hann', 'blackman', 'bohman',
    'gaussian' (as ('gaussian', std)), 'taylor', 'kaiser' ((name, beta)),
    'exponential', 'triang', 'tukey', 'bartlett', 'cosine'."""
    if isinstance(window, tuple):
        name, *params = window
    else:
        name, params = window, []
    n = win_length
    # periodic (fftbins) windows are the length-(n+1) symmetric window minus
    # the last sample
    m = n + 1 if fftbins else n
    k = np.arange(m)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / (m - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / (m - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / (m - 1))
             + 0.08 * np.cos(4 * np.pi * k / (m - 1)))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * k / (m - 1) - 1.0)
    elif name == "triang":
        if m % 2 == 0:
            w = (2 * k + 1) / m
            w = np.where(k >= m // 2, 2 - w, w)
        else:
            w = 2 * (k + 1) / (m + 1)
            w = np.where(k >= (m + 1) // 2, 2 - w, w)
    elif name == "cosine":
        w = np.sin(np.pi * (k + 0.5) / m)
    elif name == "bohman":
        x = np.abs(2 * k / (m - 1) - 1.0)
        w = (1 - x) * np.cos(np.pi * x) + np.sin(np.pi * x) / np.pi
    elif name == "gaussian":
        std = params[0] if params else 1.0
        x = k - (m - 1) / 2.0
        w = np.exp(-0.5 * (x / std) ** 2)
    elif name == "kaiser":
        beta = params[0] if params else 12.0
        w = np.kaiser(m, beta)
    elif name == "exponential":
        # reference convention: ('exponential', center, tau)
        center = params[0] if len(params) > 0 else None
        tau = params[1] if len(params) > 1 else 1.0
        if center is None:
            center = (m - 1) / 2.0
        if tau is None:
            tau = 1.0
        x = np.abs(k - center)
        w = np.exp(-x / tau)
    elif name == "tukey":
        alpha = params[0] if params else 0.5
        w = np.ones(m)
        width = int(alpha * (m - 1) / 2.0)
        if width > 0:
            edge = 0.5 * (1 + np.cos(np.pi * (-1 + 2.0 * k[:width + 1] /
                                              alpha / (m - 1))))
            w[:width + 1] = edge
            w[-(width + 1):] = edge[::-1]
    elif name == "taylor":
        # 4-term, 30dB sidelobe Taylor window (scipy default parameters)
        defaults = [4, 30]
        defaults[:len(params)] = list(params)[:2]
        nbar, sll = defaults
        B = 10 ** (sll / 20)
        A = np.arccosh(B) / np.pi
        s2 = nbar ** 2 / (A ** 2 + (nbar - 0.5) ** 2)
        ma = np.arange(1, nbar)
        Fm = np.zeros(nbar - 1)
        signs = (-1) ** (ma + 1)
        m2 = ma ** 2
        for mi, _ in enumerate(ma):
            numer = signs[mi] * np.prod(
                1 - m2[mi] / s2 / (A ** 2 + (ma - 0.5) ** 2))
            denom = 2 * np.prod(1 - m2[mi] / m2[:mi]) * np.prod(
                1 - m2[mi] / m2[mi + 1:])
            Fm[mi] = numer / denom
        pos = (k - (m - 1) / 2.0) / m
        w = np.ones(m)
        for mi, _ in enumerate(ma):
            w = w + 2 * Fm[mi] * np.cos(2 * np.pi * ma[mi] * pos)
        w /= w.max()
    else:
        raise ValueError(f"unsupported window {window!r}")
    if fftbins:
        w = w[:-1]
    return Tensor(jnp.asarray(w, dtype=_np_dtype(dtype)))


def _np_dtype(dtype):
    from ..core.dtype import to_jax_dtype

    return to_jax_dtype(dtype)


# --------------------------------------------------------------- mel scale

def hz_to_mel(freq, htk=False):
    """ref functional.hz_to_mel: Slaney (default) or HTK formula."""
    scalar = not isinstance(freq, Tensor)
    f = _as_t(freq)._data if not scalar else np.asarray(freq, np.float64)
    if htk:
        mel = 2595.0 * (jnp.log10(1.0 + f / 700.0) if not scalar
                        else np.log10(1.0 + f / 700.0))
        return Tensor(mel) if not scalar else float(mel)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if scalar:
        if f >= min_log_hz:
            mels = min_log_mel + np.log(f / min_log_hz) / logstep
        return float(mels)
    mels = jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(f / min_log_hz) / logstep, mels)
    return Tensor(mels)


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, Tensor)
    m = _as_t(mel)._data if not scalar else np.asarray(mel, np.float64)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        return float(hz) if scalar else Tensor(hz)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if scalar:
        if m >= min_log_mel:
            freqs = min_log_hz * np.exp(logstep * (m - min_log_mel))
        return float(freqs)
    freqs = jnp.where(m >= min_log_mel,
                      min_log_hz * jnp.exp(logstep * (m - min_log_mel)), freqs)
    return Tensor(freqs)


def _mel_freqs_np(n_mels, f_min, f_max, htk):
    """Mel-spaced frequencies in numpy float64 (filterbank construction is
    host-side one-time math; jax default f32 would lose precision)."""
    lo = hz_to_mel(f_min, htk=htk)
    hi = hz_to_mel(f_max, htk=htk)
    mels = np.linspace(lo, hi, n_mels)
    return np.array([mel_to_hz(float(m), htk=htk) for m in mels])


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    return Tensor(jnp.asarray(_mel_freqs_np(n_mels, f_min, f_max, htk),
                              _np_dtype(dtype)))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.asarray(np.linspace(0.0, sr / 2.0, 1 + n_fft // 2),
                              _np_dtype(dtype)))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1+n_fft//2] (ref
    compute_fbank_matrix)."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = np.linspace(0.0, sr / 2.0, 1 + n_fft // 2)
    melfreqs = _mel_freqs_np(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(jnp.asarray(weights, _np_dtype(dtype)))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (ref create_dct)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :]) * 2.0
    if norm == "ortho":
        dct[:, 0] *= math.sqrt(1.0 / (4 * n_mels))
        dct[:, 1:] *= math.sqrt(1.0 / (2 * n_mels))
    return Tensor(jnp.asarray(dct, _np_dtype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10(S/ref) with clamping (ref power_to_db)."""
    x = _as_t(spect)

    def f(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec

    return apply(f, x, _op_name="power_to_db")
