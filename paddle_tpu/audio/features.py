"""paddle.audio.features parity (ref: python/paddle/audio/features/layers.py
(U)): Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC as nn.Layers."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_call import apply
from ..nn.layer.layers import Layer
from ..tensor.creation import _as_t
from ..tensor.math import matmul
from . import functional as AF


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 fftbins=True, dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = AF.get_window(window, self.win_length,
                                        fftbins=fftbins, dtype=dtype)

    def forward(self, x):
        from ..signal import stft

        sp = stft(x, self.n_fft, self.hop_length, self.win_length,
                  self.fft_window, self.center, self.pad_mode)
        return apply(lambda s: jnp.abs(s) ** self.power, sp,
                     _op_name="spectrogram")


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype=dtype)
        self.n_mels = n_mels
        self.fbank_matrix = AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype)

    def forward(self, x):
        sp = self._spectrogram(x)  # [..., freq, frames]
        return matmul(self.fbank_matrix, sp)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = AF.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        log_mel = self._log_melspectrogram(x)  # [..., n_mels, frames]
        from ..tensor.manipulation import swapaxes

        # [n_mels, n_mfcc]^T @ [..., n_mels, frames] -> [..., n_mfcc, frames]
        return matmul(swapaxes(self.dct_matrix, 0, 1), log_mel)
