"""paddle.audio parity (features subset)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_call import apply
from ..tensor.creation import _as_t


class functional:
    @staticmethod
    def create_dct(n_mfcc, n_mels, norm="ortho"):
        import numpy as np

        from ..core.tensor import Tensor

        n = np.arange(n_mels)
        k = np.arange(n_mfcc)[:, None]
        dct = np.cos(np.pi / n_mels * (n + 0.5) * k)
        if norm == "ortho":
            dct[0] *= 1.0 / np.sqrt(2)
            dct *= np.sqrt(2.0 / n_mels)
        return Tensor(dct.T.astype(np.float32))

    @staticmethod
    def hz_to_mel(freq, htk=False):
        import math

        if htk:
            return 2595.0 * math.log10(1.0 + freq / 700.0)
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (freq - f_min) / f_sp
        min_log_hz = 1000.0
        if freq >= min_log_hz:
            min_log_mel = (min_log_hz - f_min) / f_sp
            logstep = math.log(6.4) / 27.0
            mels = min_log_mel + math.log(freq / min_log_hz) / logstep
        return mels
