"""paddle.audio parity (ref: python/paddle/audio/ (U)): window/mel/dct
functional plus Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC feature
layers. Dataset/backend IO (load/save, soundfile backends) is out of scope in
a zero-egress build — features operate on tensors."""

from . import functional
from . import features
from .features import Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
