"""Beam-search decoding (ref: python/paddle/nn/decode.py (U):
BeamSearchDecoder + dynamic_decode).

TPU stance: decode is an eager host loop over jitted cell steps — the
data-dependent stopping condition lives in Python (the reference's
dynamic_decode while_op does the same job in-graph); each step's math is
plain jax ops so XLA compiles/caches the step. Layout batch-major
[batch, beam, ...], outputs [batch, time, beam] like the reference's
default output_time_major=False.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import tape as _tape
from ..tensor.creation import _as_t


class BeamSearchDecoder:
    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # ---- helpers -----------------------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] (reference helper)."""
        a = _as_t(x)._data
        tiled = jnp.repeat(a[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + a.shape[1:]))

    def _merge(self, a):  # [batch, beam, ...] -> [batch*beam, ...]
        return a.reshape((-1,) + a.shape[2:])

    def _split(self, a, batch):  # [batch*beam, ...] -> [batch, beam, ...]
        return a.reshape((batch, self.beam_size) + a.shape[1:])

    # ---- protocol ----------------------------------------------------
    def initialize(self, initial_cell_states):
        states = initial_cell_states
        leaves = states if isinstance(states, (tuple, list)) else (states,)
        batch = int(_as_t(leaves[0]).shape[0])
        tiled = [self.tile_beam_merge_with_batch(s, self.beam_size)._data
                 for s in leaves]
        cell_states = (tuple(Tensor(t) for t in tiled)
                       if isinstance(states, (tuple, list))
                       else Tensor(tiled[0]))
        ids = jnp.full((batch, self.beam_size), self.start_token, jnp.int32)
        log_probs = jnp.where(
            jnp.arange(self.beam_size)[None, :] == 0, 0.0, -1e9
        ) * jnp.ones((batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        inputs = self._embed(ids.reshape(-1))
        return inputs, (cell_states, log_probs, finished), batch

    def _embed(self, flat_ids):
        if self.embedding_fn is not None:
            return self.embedding_fn(Tensor(flat_ids))
        return Tensor(flat_ids)

    def step(self, time, inputs, states, batch):
        cell_states, log_probs, finished = states
        out = self.cell(inputs, cell_states)
        # RNN cells return (output, new_states)
        cell_out, new_cell_states = out if isinstance(out, tuple) and \
            len(out) == 2 else (out, cell_states)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = _as_t(cell_out)._data  # [batch*beam, vocab]
        vocab = logits.shape[-1]
        import jax

        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        step_lp = self._split(step_lp, batch)  # [batch, beam, vocab]
        # finished beams only extend with end_token at prob 1
        fin_mask = jnp.full((vocab,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], fin_mask[None, None, :],
                            step_lp)
        total = log_probs[..., None] + step_lp  # [batch, beam, vocab]
        flat = total.reshape(batch, -1)
        top_lp, top_idx = jax.lax.top_k(flat, self.beam_size)
        parent = (top_idx // vocab).astype(jnp.int32)   # [batch, beam]
        token = (top_idx % vocab).astype(jnp.int32)
        # reorder states by parent beam
        def reorder(leaf):
            a = self._split(_as_t(leaf)._data, batch)
            ga = jnp.take_along_axis(
                a, parent.reshape(parent.shape + (1,) * (a.ndim - 2)), axis=1)
            return Tensor(self._merge(ga))

        if isinstance(new_cell_states, (tuple, list)):
            new_cell_states = tuple(reorder(s) for s in new_cell_states)
        else:
            new_cell_states = reorder(new_cell_states)
        new_finished = jnp.take_along_axis(finished, parent, axis=1) | \
            (token == self.end_token)
        next_inputs = self._embed(token.reshape(-1))
        return (token, parent, top_lp,
                next_inputs, (new_cell_states, top_lp, new_finished),
                new_finished)


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run the decoder to completion (all beams finished or max steps)."""
    with _tape.no_grad():
        inputs, states, batch = decoder.initialize(inits)
        tokens, parents = [], []
        seq_len = jnp.zeros((batch, decoder.beam_size), jnp.int32)
        finished = states[2]
        for t in range(int(max_step_num)):
            token, parent, lp, inputs, states, finished = decoder.step(
                t, inputs, states, batch)
            tokens.append(token)
            parents.append(parent)
            seq_len = seq_len + (~finished).astype(jnp.int32)
            if bool(finished.all()):
                break
        ids = jnp.stack(tokens)      # [time, batch, beam]
        par = jnp.stack(parents)
        from .functional.common import gather_tree

        full = gather_tree(Tensor(ids), Tensor(par))._data
        if not output_time_major:
            full = jnp.transpose(full, (1, 0, 2))  # [batch, time, beam]
        out = Tensor(full)
        if return_length:
            return out, states[0], Tensor(seq_len)
        return out, states[0]
