"""paddle.nn.functional parity namespace."""

from .activation import (
    relu, relu_, relu6, gelu, silu, swish, sigmoid, hardsigmoid, hardswish,
    hardtanh, hardshrink, tanh, tanhshrink, leaky_relu, prelu, rrelu, elu,
    selu, celu, mish, softplus, softshrink, softsign, thresholded_relu,
    log_sigmoid, softmax, softmax_, log_softmax, gumbel_softmax, glu, maxout,
)
from .common import (
    linear, dropout, dropout2d, dropout3d, alpha_dropout, pad, zeropad2d,
    embedding, one_hot, cosine_similarity, pixel_shuffle, pixel_unshuffle,
    channel_shuffle, interpolate, upsample, unfold, fold, label_smooth, bilinear,
    sequence_mask, pairwise_distance, gather_tree, sparse_attention,
)
from .vision import grid_sample, affine_grid, temporal_shift
from .conv import (
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose, conv3d_transpose,
)
from .norm import (
    layer_norm, batch_norm, group_norm, instance_norm, rms_norm, normalize,
    local_response_norm,
)
from .pooling import (
    max_pool1d, max_pool2d, max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d,
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
    max_unpool1d, max_unpool2d, max_unpool3d,
)
from .loss import (
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    kl_div, margin_ranking_loss, cosine_embedding_loss, hinge_embedding_loss,
    triplet_margin_loss, square_error_cost, sigmoid_focal_loss, log_loss,
    ctc_loss, margin_cross_entropy, gaussian_nll_loss, poisson_nll_loss,
    soft_margin_loss, multi_label_soft_margin_loss, multi_margin_loss,
    triplet_margin_with_distance_loss, dice_loss, npair_loss, hsigmoid_loss,
)
from .attention import (
    scaled_dot_product_attention, flash_attention, flash_attn_unpadded, sdp_kernel,
)

from . import flash_attention as flash_attention_module  # noqa: F401


def elu_(x, alpha=1.0, name=None):
    """In-place elu (reference elu_): mutates the Tensor's buffer."""
    out = elu(x, alpha)
    x._data = out._data
    return x
