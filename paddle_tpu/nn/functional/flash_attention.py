"""paddle.nn.functional.flash_attention submodule parity
(ref: python/paddle/nn/functional/flash_attention.py (U))."""

from .attention import (
    flash_attention, flash_attn_unpadded, scaled_dot_product_attention, sdp_kernel,
)

flash_attn_qkvpacked = None  # packed variants are unpacked on TPU (static shapes)
