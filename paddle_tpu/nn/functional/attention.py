"""Attention functionals.

Reference parity: paddle.nn.functional.flash_attention /
scaled_dot_product_attention backed by the vendored FlashAttention-2 CUDA lib
(SURVEY.md §2.1 N5). TPU-native: routes to the Pallas flash-attention kernel
(paddle_tpu.ops.flash_attention) on TPU, with a pure-XLA fallback elsewhere —
same signature, same [batch, seq, heads, head_dim] layout as the reference.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.op_call import apply
from ...core.tensor import Tensor
from ...tensor.creation import _as_t


def _sdpa_ref(q, k, v, mask=None, dropout_p=0.0, causal=False, scale=None, key=None):
    # q,k,v: [B, S, H, D] (paddle flash-attn layout); GQA via shared helper
    from ...ops.flash_attention import expand_kv_heads

    k, v = expand_kv_heads(q, k, v)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bshd,bthd->bhst", qf, kf) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    p = jax.nn.softmax(logits, axis=-1)
    # fully-masked rows (causal q_len > kv_len, or an all-False mask row)
    # must output 0, matching the flash-attn convention of the Pallas path
    # — plain softmax would instead spread uniformly and return mean(v)
    row_has_key = jnp.any(logits > -1e29, axis=-1, keepdims=True)
    p = jnp.where(row_has_key, p, 0.0)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhst,bthd->bshd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _use_pallas(q_shape, head_dim):
    try:
        import jax

        if jax.default_backend() != "tpu":
            return False
        # long-enough seq; non-lane-aligned head dims (<=256) are padded
        # to 128 lanes by ops.flash_attention (free on the MXU)
        return head_dim <= 256 and q_shape[1] >= 128
    except Exception:
        return False


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Inputs [batch, seq, num_heads, head_dim] (paddle parity)."""
    q, k, v = _as_t(query), _as_t(key), _as_t(value)
    rng_key = None
    if dropout_p > 0.0 and training:
        from ...core import random_state

        rng_key = random_state.next_key()

    if (attn_mask is None and _use_pallas(tuple(q.shape), q.shape[-1])
            and dropout_p == 0.0 and q.shape[2] % k.shape[2] == 0):
        # GQA handled natively by the kernel (kv heads shared via index map)
        from ...ops.flash_attention import flash_attention as pallas_flash

        return pallas_flash(q, k, v, causal=is_causal)

    mask_t = _as_t(attn_mask).detach() if attn_mask is not None else None
    args = [q, k, v] + ([mask_t] if mask_t is not None else [])

    def f(qa, ka, va, *m):
        return _sdpa_ref(qa, ka, va, m[0] if m else None,
                         dropout_p if training else 0.0, is_causal, key=rng_key)

    return apply(f, *args, _op_name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal, training)
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale=None, dropout=0.0, causal=False,
                        return_softmax=False, training=True, name=None):
    """Varlen flash-attn parity: the TPU design keeps static shapes (XLA
    requirement) — callers should batch to max_seqlen with masks instead.
    Provided eagerly for API completeness."""
    q, k, v = _as_t(query), _as_t(key), _as_t(value)
    import numpy as np

    cq = np.asarray(_as_t(cu_seqlens_q)._data)
    ck = np.asarray(_as_t(cu_seqlens_k)._data)
    outs = []
    for i in range(len(cq) - 1):
        qi = q[int(cq[i]):int(cq[i + 1])]
        ki = k[int(ck[i]):int(ck[i + 1])]
        vi = v[int(ck[i]):int(ck[i + 1])]
        o = scaled_dot_product_attention(
            qi.unsqueeze(0), ki.unsqueeze(0), vi.unsqueeze(0), None, dropout, causal, training
        )
        outs.append(o.squeeze(0))
    from ...tensor.manipulation import concat

    out = concat(outs, axis=0)
    return (out, None) if return_softmax else (out, None)


def sdp_kernel(*args, **kwargs):
    import contextlib

    return contextlib.nullcontext()
