"""Activation functionals (ref: python/paddle/nn/functional/activation.py (U)).
All map to jax.nn primitives — XLA fuses them into adjacent matmuls on TPU."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_call import apply
from ...core.tensor import Tensor
from ...tensor.creation import _as_t


def _u(fn, x, name=None):
    return apply(fn, _as_t(x), _op_name=name or getattr(fn, "__name__", "act"))


def relu(x, name=None):
    return _u(jax.nn.relu, x, "relu")


def relu_(x, name=None):
    x._data = jax.nn.relu(x._data)
    return x


def relu6(x, name=None):
    return _u(jax.nn.relu6, x, "relu6")


def gelu(x, approximate=False, name=None):
    return _u(lambda a: jax.nn.gelu(a, approximate=approximate), x, "gelu")


def silu(x, name=None):
    return _u(jax.nn.silu, x, "silu")


def swish(x, name=None):
    return silu(x)


def sigmoid(x, name=None):
    return _u(jax.nn.sigmoid, x, "sigmoid")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _u(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x, "hardsigmoid")


def hardswish(x, name=None):
    return _u(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x, "hardswish")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _u(lambda a: jnp.clip(a, min, max), x, "hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return _u(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x, "hardshrink")


def tanh(x, name=None):
    return _u(jnp.tanh, x, "tanh")


def tanhshrink(x, name=None):
    return _u(lambda a: a - jnp.tanh(a), x, "tanhshrink")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _u(lambda a: jax.nn.leaky_relu(a, negative_slope), x, "leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a >= 0, a, wb * a)

    return apply(f, _as_t(x), _as_t(weight), _op_name="prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    if training:
        from ...core import random_state

        key = random_state.next_key()

        def f(a):
            r = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, r * a)

        return apply(f, _as_t(x), _op_name="rrelu")
    mid = (lower + upper) / 2.0
    return _u(lambda a: jnp.where(a >= 0, a, mid * a), x, "rrelu")


def elu(x, alpha=1.0, name=None):
    return _u(lambda a: jax.nn.elu(a, alpha), x, "elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _u(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x, "selu")


def celu(x, alpha=1.0, name=None):
    return _u(lambda a: jax.nn.celu(a, alpha), x, "celu")


def mish(x, name=None):
    return _u(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x, "mish")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _u(lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta), x, "softplus")


def softshrink(x, threshold=0.5, name=None):
    return _u(lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)), x, "softshrink")


def softsign(x, name=None):
    return _u(jax.nn.soft_sign, x, "softsign")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return _u(lambda a: jnp.where(a > threshold, a, value), x, "thresholded_relu")


def log_sigmoid(x, name=None):
    return _u(jax.nn.log_sigmoid, x, "log_sigmoid")


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import to_jax_dtype

    jd = to_jax_dtype(dtype) if dtype else None

    def f(a):
        if jd is not None:
            a = a.astype(jd)
        return jax.nn.softmax(a, axis=axis)

    return _u(f, x, "softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    x._data = jax.nn.softmax(x._data, axis=axis)
    return x


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import to_jax_dtype

    jd = to_jax_dtype(dtype) if dtype else None

    def f(a):
        if jd is not None:
            a = a.astype(jd)
        return jax.nn.log_softmax(a, axis=axis)

    return _u(f, x, "log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...tensor.random import gumbel_softmax as _gs

    return _gs(x, temperature, hard, axis)


def glu(x, axis=-1, name=None):
    return _u(lambda a: jax.nn.glu(a, axis=axis), x, "glu")


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return _u(f, x, "maxout")
