"""Vision functionals (ref: python/paddle/nn/functional/vision.py (U):
grid_sample/affine_grid backed by CUDA kernels; temporal_shift in
paddle/fluid/operators). TPU-native: pure gather/arithmetic, fully jittable
with static shapes."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_call import apply
from ...tensor.creation import _as_t


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect(x, lo, hi):
    """Reflect coordinates into [lo, hi] (scipy 'reflect' with half-sample
    offsets folded in by the caller)."""
    rng = hi - lo
    if rng <= 0:
        return jnp.zeros_like(x)
    x = jnp.abs(x - lo) % (2 * rng)
    return lo + jnp.where(x > rng, 2 * rng - x, x)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """ref F.grid_sample: x [N,C,H,W], grid [N,Hg,Wg,2] in [-1,1] (xy order)
    -> [N,C,Hg,Wg]."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode {mode!r} not supported")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"grid_sample padding_mode {padding_mode!r}")

    xt, gt = _as_t(x), _as_t(grid)

    def f(img, g):
        n, c, h, w = img.shape
        gx = _unnormalize(g[..., 0], w, align_corners)   # [N,Hg,Wg]
        gy = _unnormalize(g[..., 1], h, align_corners)

        if padding_mode == "reflection":
            if align_corners:
                gx = _reflect(gx, 0.0, w - 1.0)
                gy = _reflect(gy, 0.0, h - 1.0)
            else:
                gx = jnp.clip(_reflect(gx, -0.5, w - 0.5), 0, w - 1)
                gy = jnp.clip(_reflect(gy, -0.5, h - 0.5), 0, h - 1)
        elif padding_mode == "border":
            gx = jnp.clip(gx, 0, w - 1)
            gy = jnp.clip(gy, 0, h - 1)

        def gather(iy, ix):
            """img[n, :, iy, ix] with out-of-range -> 0; iy/ix [N,Hg,Wg]."""
            inside = ((iy >= 0) & (iy <= h - 1) & (ix >= 0) & (ix <= w - 1))
            iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            out = jax.vmap(lambda im, yy, xx: im[:, yy, xx])(img, iyc, ixc)
            # out [N, C, Hg, Wg]; mask out-of-range (zeros padding)
            return out * inside[:, None].astype(img.dtype)

        if mode == "nearest":
            return gather(jnp.round(gy), jnp.round(gx))

        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        x1 = x0 + 1
        y1 = y0 + 1
        wx = (gx - x0)[:, None]
        wy = (gy - y0)[:, None]
        v00 = gather(y0, x0)
        v01 = gather(y0, x1)
        v10 = gather(y1, x0)
        v11 = gather(y1, x1)
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    return apply(f, xt, gt, _op_name="grid_sample")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """ref F.affine_grid: theta [N,2,3] -> sampling grid [N,H,W,2]."""
    th = _as_t(theta)
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy()]
    n, c, h, w = [int(v) for v in out_shape]

    def f(t):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, w)
            ys = jnp.linspace(-1.0, 1.0, h)
        else:
            xs = (jnp.arange(w) * 2 + 1) / w - 1.0
            ys = (jnp.arange(h) * 2 + 1) / h - 1.0
        gx, gy = jnp.meshgrid(xs, ys)                    # [H, W]
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
        # [N,H,W,2] = base @ theta^T
        return jnp.einsum("hwk,njk->nhwj", base, t)

    return apply(f, th, _op_name="affine_grid")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """ref temporal_shift op (TSM): shift 1/r channels forward in time,
    1/r backward, rest unchanged. x [N*T, C, H, W]."""
    xt = _as_t(x)
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(data_format)

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        pad = jnp.pad(a, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        fwd = pad[:, :seg_num, :fold]           # shift left (from t-1)
        bwd = pad[:, 2:, fold:2 * fold]         # shift right (from t+1)
        keep = a[:, :, 2 * fold:]
        out = jnp.concatenate([fwd, bwd, keep], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply(f, xt, _op_name="temporal_shift")
