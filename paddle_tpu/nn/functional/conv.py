"""Convolutions over lax.conv_general_dilated (ref: phi conv kernels via cuDNN,
SURVEY.md §2.1 N3). On TPU, XLA lowers these straight onto the MXU — the
cuDNN-algorithm-selection machinery of the reference has no equivalent and
isn't needed. Weight layout follows paddle: [out_c, in_c/groups, *spatial].
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ...core.op_call import apply
from ...tensor.creation import _as_t


def _norm_tuple(v, n):
    if isinstance(v, (list, tuple)):
        out = list(v)
        if len(out) == 1:
            out = out * n
        return tuple(int(x) for x in out)
    return (int(v),) * n


def _norm_padding(padding, n, strides=None):
    """Returns list of (lo, hi) per spatial dim, or the string SAME/VALID."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = list(padding)
        if len(flat) == n:
            return [(int(p), int(p)) for p in flat]
        if len(flat) == 2 * n:
            return [(int(flat[2 * i]), int(flat[2 * i + 1])) for i in range(n)]
        if all(isinstance(p, (list, tuple)) for p in flat):
            # NCHW-style [[0,0],[0,0],[ph,ph],[pw,pw]]
            sp = flat[-n:]
            return [(int(p[0]), int(p[1])) for p in sp]
    return [(int(padding), int(padding))] * n


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    pad = _norm_padding(padding, n)
    dn = _dim_numbers(n, channel_last)

    def f(a, w, *b):
        # paddle weight layout is [O, I/g, *spatial] == OIHW; lax wants per dn
        if channel_last:
            # convert OIHW -> HWIO
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w = w.transpose(perm)
        out = lax.conv_general_dilated(
            a, w,
            window_strides=stride,
            padding=pad,
            lhs_dilation=None,
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=groups,
        )
        if b:
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b[0].shape[0]
            out = out + b[0].reshape(shape)
        return out

    args = [_as_t(x), _as_t(weight)]
    if bias is not None:
        args.append(_as_t(bias))
    return apply(f, *args, _op_name=f"conv{n}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, n, data_format, output_size=None):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    pad = _norm_padding(padding, n)
    opad = _norm_tuple(output_padding, n) if output_padding else (0,) * n
    dn = _dim_numbers(n, channel_last)

    def f(a, w, *b):
        # paddle transpose-conv weight layout: [in_c, out_c/g, *spatial] (IOHW)
        if groups > 1:
            # lax handles grouped transposed conv via feature_group_count on the
            # gradient formulation: reshape to (I, O/g, ...) blocks
            pass
        # Use conv_general_dilated with lhs_dilation (fractionally-strided conv)
        k_eff = [dilation[i] * (w.shape[2 + i] - 1) + 1 for i in range(n)]
        if isinstance(pad, str):
            if pad == "VALID":
                pads = [(0, 0)] * n
            else:  # SAME: output spatial = input * stride
                pads = []
                for i in range(n):
                    total = max(k_eff[i] - stride[i], 0)
                    pads.append((total // 2, total - total // 2))
        else:
            pads = pad
        trans_pads = [
            (k_eff[i] - 1 - pads[i][0], k_eff[i] - 1 - pads[i][1] + opad[i])
            for i in range(n)
        ]
        # weight IOHW -> flip spatial, swap I/O => OIHW for the underlying conv
        w2 = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups == 1:
            w2 = jnp.swapaxes(w2, 0, 1)
        else:
            ic, ocg = w2.shape[0], w2.shape[1]
            w2 = w2.reshape((groups, ic // groups) + w2.shape[1:])
            w2 = jnp.swapaxes(w2, 1, 2)  # g, O/g, I/g, ...
            w2 = w2.reshape((ocg * groups, ic // groups) + w2.shape[3:])
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w2 = w2.transpose(perm)
        out = lax.conv_general_dilated(
            a, w2,
            window_strides=(1,) * n,
            padding=trans_pads,
            lhs_dilation=stride,
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=groups,
        )
        if b:
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b[0].shape[0]
            out = out + b[0].reshape(shape)
        return out

    args = [_as_t(x), _as_t(weight)]
    if bias is not None:
        args.append(_as_t(bias))
    return apply(f, *args, _op_name=f"conv{n}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 1, df, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 3, data_format, output_size)
