"""Common functionals: linear, dropout, pad, embedding, interpolate...
(ref: python/paddle/nn/functional/common.py + input.py (U))."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.op_call import apply
from ...core.tensor import Tensor
from ...core import random_state
from ...tensor.creation import _as_t


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W shaped [in, out] (paddle convention — already the
    MXU-friendly layout; no transpose needed)."""
    if bias is None:
        return apply(lambda a, w: a @ w, _as_t(x), _as_t(weight), _op_name="linear")
    return apply(lambda a, w, b: a @ w + b, _as_t(x), _as_t(weight), _as_t(bias), _op_name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = _as_t(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1.0 - p), x)
        return x.clone() if not isinstance(x, Tensor) else x
    key = random_state.next_key()

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(f, x, _op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=list(ax), training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=list(ax), training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _as_t(x)
    if not training or p == 0.0:
        return x
    key = random_state.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * p * alpha_p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return apply(f, x, _op_name="alpha_dropout")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _as_t(x)
    if isinstance(pad, Tensor):
        pad = [int(v) for v in np.asarray(pad._data)]
    pad = [int(v) for v in pad]

    nd = x.ndim
    if len(pad) == 2 * nd:
        # full-form pad: [before0, after0, before1, after1, ...]? paddle uses per-dim pairs
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial spatial pad, paddle order: last spatial dims, reversed pairs
        spatial = len(pad) // 2
        widths = [(0, 0)] * nd
        if data_format.startswith("NC"):
            dims = list(range(2, 2 + spatial))
        else:
            dims = list(range(1, 1 + spatial))
        # paddle pad order is [left, right, top, bottom, ...] i.e. innermost dim first
        for i, d in enumerate(reversed(dims)):
            widths[d] = (pad[2 * i], pad[2 * i + 1])

    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]

    def f(a):
        if jmode == "constant":
            return jnp.pad(a, widths, mode="constant", constant_values=value)
        return jnp.pad(a, widths, mode=jmode)

    return apply(f, x, _op_name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(w, i):
        i = i.astype(jnp.int32)
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply(f, _as_t(weight), _as_t(x), _op_name="embedding")


def one_hot(x, num_classes, name=None):
    from ...core.dtype import get_default_dtype

    return apply(lambda i: jax.nn.one_hot(i.astype(jnp.int32), num_classes, dtype=get_default_dtype()), _as_t(x))


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)

    return apply(f, _as_t(x1), _as_t(x2), _op_name="cosine_similarity")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return apply(f, _as_t(x), _op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)

    return apply(f, _as_t(x), _op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w).transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups).transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)

    return apply(f, _as_t(x), _op_name="channel_shuffle")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    x = _as_t(x)
    spatial_ndim = x.ndim - 2
    if data_format.startswith("NC"):
        spatial = x.shape[2:]
    else:
        spatial = x.shape[1:-1]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in np.asarray(size._data)]
        out_size = [int(s._data) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * spatial_ndim)]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial_ndim
        out_size = [int(s * f) for s, f in zip(spatial, sf)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def f(a):
        if data_format.startswith("NC"):
            tgt_shape = a.shape[:2] + tuple(out_size)
        else:
            tgt_shape = (a.shape[0],) + tuple(out_size) + (a.shape[-1],)
        if mode == "nearest":
            return jax.image.resize(a, tgt_shape, method="nearest")
        if align_corners and jmode == "linear":
            # jax.image.resize has no align_corners; emulate with explicit grid
            return _resize_align_corners(a, tgt_shape, data_format)
        return jax.image.resize(a, tgt_shape, method=jmode)

    return apply(f, x, _op_name="interpolate")


def _resize_align_corners(a, tgt_shape, data_format):
    # linear interp with corner alignment (matches paddle align_corners=True)
    src_shape = a.shape
    if data_format.startswith("NC"):
        spatial_axes = list(range(2, a.ndim))
    else:
        spatial_axes = list(range(1, a.ndim - 1))
    out = a
    for ax in spatial_axes:
        n_in = src_shape[ax]
        n_out = tgt_shape[ax]
        if n_in == n_out:
            continue
        pos = jnp.linspace(0.0, n_in - 1.0, n_out)
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, n_in - 1)
        w = (pos - lo).reshape([-1 if i == ax else 1 for i in range(a.ndim)])
        lo_v = jnp.take(out, lo, axis=ax)
        hi_v = jnp.take(out, hi, axis=ax)
        out = lo_v * (1 - w) + hi_v * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = a[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0], j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return apply(f, _as_t(x), _op_name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + 2 * pd[0], os_[1] + 2 * pd[1]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        a = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(a[:, :, i, j])
        return out[:, :, pd[0]: ph - pd[0], pd[1]: pw - pd[1]]

    return apply(f, _as_t(x), _op_name="fold")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) else jnp.asarray(prior_dist)
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k

    return apply(f, _as_t(label), _op_name="label_smooth")


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi:
            out = out + bi[0]
        return out

    args = [_as_t(x1), _as_t(x2), _as_t(weight)]
    if bias is not None:
        args.append(_as_t(bias))
    return apply(f, *args, _op_name="bilinear")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """ref F.sequence_mask: lengths -> [..., maxlen] 0/1 mask.

    NOTE: with maxlen=None the mask width is read from the concrete input,
    so under jit/to_static tracing `maxlen` must be passed explicitly
    (static output shapes are an XLA requirement)."""
    import jax.numpy as jnp

    from ...core.dtype import to_jax_dtype
    from ...tensor.creation import _as_t
    from ...core.op_call import apply as _apply

    xt = _as_t(x)
    if maxlen is None:
        import numpy as np

        lens_np = np.asarray(xt._data)
        maxlen = int(lens_np.max()) if lens_np.size else 0

    def f(lens):
        idx = jnp.arange(maxlen)
        return (idx < lens[..., None]).astype(to_jax_dtype(dtype))

    return _apply(f, xt, _op_name="sequence_mask")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return apply(f, _as_t(x), _as_t(y), _op_name="pairwise_distance")


def gather_tree(ids, parents):
    """Beam-search backtrace (ref gather_tree op): ids/parents
    [max_time, batch, beam] -> full beams gathered from the last step."""
    def f(idv, par):
        T = idv.shape[0]

        def step(beams, t):
            # beams: [batch, beam] beam index selected at time t+1; the
            # contributing beam at time t is parents[t+1][beams]
            prev = jnp.take_along_axis(par[t + 1], beams, axis=-1)
            out = jnp.take_along_axis(idv[t], prev, axis=-1)
            return prev, out

        import jax as _jax

        init = jnp.broadcast_to(
            jnp.arange(idv.shape[2]), idv.shape[1:]).astype(par.dtype)
        last = idv[T - 1]
        _, rev = _jax.lax.scan(step, init, jnp.arange(T - 2, -1, -1))
        return jnp.concatenate([jnp.flip(rev, 0), last[None]], 0)

    return apply(f, _as_t(ids).detach(), _as_t(parents).detach(),
                 _op_name="gather_tree")


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention at a CSR-described pattern. TPU stance: the
    pattern becomes a dense bool mask consumed by the flash/SDPA path — XLA
    has no CSR attention kernel, and for the pattern sizes the reference op
    targets (per-row allowed keys) the masked dense path on the MXU is the
    faster program. Inputs [batch, heads, seq, head_dim] (reference layout)."""
    from .attention import _sdpa_ref

    q, k, v = _as_t(query), _as_t(key), _as_t(value)
    offs = _as_t(sparse_csr_offset).numpy()
    cols = _as_t(sparse_csr_columns).numpy()
    b, h, s, d = q.shape
    import numpy as np

    mask = np.zeros((b, h, s, s), bool)
    for bi in range(b):
        for hi in range(h):
            o = offs[bi, hi]
            c = cols[bi, hi]
            for r in range(s):
                mask[bi, hi, r, c[o[r]:o[r + 1]]] = True

    def f(qa, ka, va):
        qt = jnp.swapaxes(qa, 1, 2)  # -> [b, s, h, d] sdpa layout
        kt = jnp.swapaxes(ka, 1, 2)
        vt = jnp.swapaxes(va, 1, 2)
        out = _sdpa_ref(qt, kt, vt, mask=jnp.asarray(mask))
        return jnp.swapaxes(out, 1, 2)

    return apply(f, q, k, v, _op_name="sparse_attention")
