"""Normalization functionals (ref: phi layer_norm/batch_norm/group_norm
kernels, SURVEY.md §2.1 N3/N4). XLA fuses these; the Pallas fused variants in
paddle_tpu.ops provide the hand-tiled fast path and are used automatically by
the corresponding nn.Layer classes when shapes allow."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_call import apply
from ...core.tensor import Tensor
from ...tensor.creation import _as_t


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))

    def f(a, *wb):
        # fused Pallas path (ref layer_norm_kernel.cu): TPU, last-dim norm,
        # both affine params present
        if (jax.default_backend() == "tpu" and n_axes == 1
                and weight is not None and bias is not None
                and wb[0].ndim == 1 and wb[1].ndim == 1):
            from ...ops.pallas.norms import layer_norm as pallas_ln

            return pallas_ln(a, wb[0], wb[1], epsilon, interpret=False)
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [_as_t(x)]
    if weight is not None:
        args.append(_as_t(weight))
    if bias is not None:
        args.append(_as_t(bias))
    return apply(f, *args, _op_name="layer_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    x = _as_t(x)
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    use_batch_stats = training and not use_global_stats

    def bshape(ndim, c):
        s = [1] * ndim
        s[channel_axis] = c
        return s

    if use_batch_stats:
        def f(a, *wb):
            mean = jnp.mean(a, axis=reduce_axes)
            var = jnp.var(a, axis=reduce_axes)
            out = (a - mean.reshape(bshape(a.ndim, mean.size))) * jax.lax.rsqrt(
                var.reshape(bshape(a.ndim, var.size)) + epsilon
            )
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(bshape(a.ndim, wb[i].size))
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(bshape(a.ndim, wb[i].size))
            return out, mean, var

        args = [x]
        if weight is not None:
            args.append(_as_t(weight))
        if bias is not None:
            args.append(_as_t(bias))
        out, batch_mean, batch_var = apply(f, *args, _op_name="batch_norm")
        # update running stats in place (dygraph semantics)
        if running_mean is not None:
            rm = running_mean._data if isinstance(running_mean, Tensor) else running_mean
            rv = running_var._data if isinstance(running_var, Tensor) else running_var
            n = 1
            for ax in reduce_axes:
                n *= x.shape[ax]
            unbiased = batch_var._data * (n / max(n - 1, 1))
            running_mean._data = rm * momentum + batch_mean._data * (1 - momentum)
            running_var._data = rv * momentum + unbiased * (1 - momentum)
        return out

    def f(a, m, v, *wb):
        out = (a - m.reshape(bshape(a.ndim, m.size))) * jax.lax.rsqrt(v.reshape(bshape(a.ndim, v.size)) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape(a.ndim, wb[i].size))
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape(a.ndim, wb[i].size))
        return out

    args = [x, _as_t(running_mean).detach(), _as_t(running_var).detach()]
    if weight is not None:
        args.append(_as_t(weight))
    if bias is not None:
        args.append(_as_t(bias))
    return apply(f, *args, _op_name="batch_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    def f(a, *wb):
        # fused Pallas path (ref fused GroupNorm kernels, SURVEY §2.1 N4):
        # TPU, channels-first, both affine params, sample fits VMEM
        if (jax.default_backend() == "tpu" and data_format.startswith("NC")
                and weight is not None and bias is not None
                and wb[0].ndim == 1 and wb[1].ndim == 1):
            from ...ops.pallas.norms import group_norm as pallas_gn
            from ...ops.pallas.norms import group_norm_supported

            if group_norm_supported(a.shape, num_groups):
                return pallas_gn(a, wb[0], wb[1], num_groups, epsilon,
                                 interpret=False)
        if data_format.startswith("NC"):
            n, c = a.shape[0], a.shape[1]
            spatial = a.shape[2:]
            g = a.reshape((n, num_groups, c // num_groups) + spatial)
            axes = tuple(range(2, g.ndim))
            mean = jnp.mean(g, axis=axes, keepdims=True)
            var = jnp.var(g, axis=axes, keepdims=True)
            out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
            shape = [1, c] + [1] * len(spatial)
        else:
            n, c = a.shape[0], a.shape[-1]
            spatial = a.shape[1:-1]
            g = a.reshape((n,) + spatial + (num_groups, c // num_groups))
            axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
            mean = jnp.mean(g, axis=axes, keepdims=True)
            var = jnp.var(g, axis=axes, keepdims=True)
            out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
            shape = [1] * (len(spatial) + 1) + [c]
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [_as_t(x)]
    if weight is not None:
        args.append(_as_t(weight))
    if bias is not None:
        args.append(_as_t(bias))
    return apply(f, *args, _op_name="group_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim)) if data_format.startswith("NC") else tuple(range(1, a.ndim - 1))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        c = a.shape[1] if data_format.startswith("NC") else a.shape[-1]
        shape = [1] * a.ndim
        shape[1 if data_format.startswith("NC") else a.ndim - 1] = c
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [_as_t(x)]
    if weight is not None:
        args.append(_as_t(weight))
    if bias is not None:
        args.append(_as_t(bias))
    return apply(f, *args, _op_name="instance_norm")


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1, name=None):
    """RMSNorm (the reference ships it as paddle.incubate.nn.functional.fused_rms_norm)."""

    def f(a, *wb):
        ax = begin_norm_axis % a.ndim
        axes = tuple(range(ax, a.ndim))
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [_as_t(x)]
    if weight is not None:
        args.append(_as_t(weight))
    if bias is not None:
        args.append(_as_t(bias))
    return apply(f, *args, _op_name="rms_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True)
        return a / jnp.maximum(n, epsilon)

    return apply(f, _as_t(x), _op_name="normalize")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def f(a):
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[ch_axis]
        acc = jnp.zeros_like(a)
        for off in range(-half, size - half):
            sl = jnp.roll(sq, off, axis=ch_axis)
            # zero out wrapped entries
            idx = jnp.arange(c)
            valid = (idx - off >= 0) & (idx - off < c)
            shape = [1] * a.ndim
            shape[ch_axis] = c
            acc = acc + jnp.where(valid.reshape(shape), sl, 0.0)
        return a / jnp.power(k + alpha * acc / size, beta)

    return apply(f, _as_t(x), _op_name="local_response_norm")
