"""Loss functionals (ref: python/paddle/nn/functional/loss.py (U)).

cross_entropy follows paddle semantics: integer or soft labels, ignore_index,
weight, reduction, label smoothing via soft labels. The sharded-vocab variant
(c_softmax_with_cross_entropy parity) lives in distributed/parallel_layers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_call import apply
from ...core.tensor import Tensor
from ...tensor.creation import _as_t


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    args = [_as_t(input), _as_t(label).detach() if not soft_label else _as_t(label)]
    if weight is not None:
        args.append(_as_t(weight).detach())

    def f(logits, lbl, *w):
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(jnp.maximum(logits, 1e-30))
        n_class = logits.shape[axis]
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape):
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_class
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            lbl_i = lbl.astype(jnp.int32)
            if lbl_i.ndim == logits.ndim:
                lbl_i = jnp.squeeze(lbl_i, axis=axis)
            valid = lbl_i != ignore_index
            safe = jnp.where(valid, lbl_i, 0)
            if label_smoothing > 0:
                oh = jax.nn.one_hot(safe, n_class, dtype=logp.dtype, axis=axis)
                oh = oh * (1 - label_smoothing) + label_smoothing / n_class
                nll = -jnp.sum(oh * logp, axis=axis)
            else:
                nll = -jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
            if w:
                cw = jnp.take(w[0], safe)
                nll = nll * cw
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(valid, cw, 0.0))
                    return jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(denom, 1e-12)
            loss = jnp.where(valid, nll, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
        return _reduce(loss, reduction)

    return apply(f, *args, _op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = apply(lambda l: jnp.expand_dims(l, axis), loss)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    args = [_as_t(input), _as_t(label).detach()]
    if weight is not None:
        args.append(_as_t(weight).detach())

    def f(logp, lbl, *w):
        lbl_i = lbl.astype(jnp.int32)
        valid = lbl_i != ignore_index
        safe = jnp.where(valid, lbl_i, 0)
        nll = -jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        if w:
            cw = jnp.take(w[0], safe)
            nll = nll * cw
            if reduction == "mean":
                denom = jnp.sum(jnp.where(valid, cw, 0.0))
                return jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(denom, 1e-12)
        nll = jnp.where(valid, nll, 0.0)
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
        return _reduce(nll, reduction)

    return apply(f, *args, _op_name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction), _as_t(input), _as_t(label), _op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), _as_t(input), _as_t(label), _op_name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply(f, _as_t(input), _as_t(label), _op_name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    args = [_as_t(input), _as_t(label)]
    if weight is not None:
        args.append(_as_t(weight))

    def f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return apply(f, *args, _op_name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    args = [_as_t(logit), _as_t(label)]
    if weight is not None:
        args.append(_as_t(weight))

    def f(z, y, *w):
        # numerically-stable BCE-with-logits
        neg_abs = -jnp.abs(z)
        if pos_weight is not None:
            pw = pos_weight._data if isinstance(pos_weight, Tensor) else jnp.asarray(pos_weight)
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(neg_abs)) + jnp.maximum(-z, 0))
        else:
            loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(neg_abs))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return apply(f, *args, _op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            loss = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-30)) - lp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return apply(f, _as_t(input), _as_t(label), _op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        _as_t(input), _as_t(other), _as_t(label), _op_name="margin_ranking_loss",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply(f, _as_t(input1), _as_t(input2), _as_t(label), _op_name="cosine_embedding_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply(
        lambda a, y: _reduce(jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        _as_t(input), _as_t(label), _op_name="hinge_embedding_loss",
    )


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + epsilon, p), axis=-1), 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + epsilon, p), axis=-1), 1 / p)
        if swap:
            dsn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + epsilon, p), axis=-1), 1 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply(f, _as_t(input), _as_t(positive), _as_t(negative), _op_name="triplet_margin_loss")


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), _as_t(input), _as_t(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def f(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    args = [_as_t(logit), _as_t(label)]
    if normalizer is not None:
        args.append(_as_t(normalizer))
    return apply(f, *args, _op_name="sigmoid_focal_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        _as_t(input), _as_t(label), _op_name="log_loss",
    )


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via the classic forward algorithm in log space (lax.scan over time).
    Shapes: log_probs [T, B, C] (paddle convention)."""

    def f(lp, lbl, in_len, lbl_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        S = lbl.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        L = 2 * lbl_len.astype(jnp.int32) + 1
        neg_inf = -1e30

        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0])

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_fn(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_fn, alpha0, jnp.arange(1, T))
        idx_last = L - 1
        idx_prev = jnp.maximum(L - 2, 0)
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(a_last, a_prev)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)

    return apply(
        f, _as_t(log_probs), _as_t(labels).detach(), _as_t(input_lengths).detach(),
        _as_t(label_lengths).detach(), _op_name="ctc_loss",
    )


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """ref F.margin_cross_entropy (ArcFace/CosFace combined margin):
    cos(m1*theta + m2) - m3 applied to the target logit, then scaled CE.
    The reference's class-parallel (group) path maps to vocab-parallel CE
    under GSPMD; here logits are the full class dim."""
    import jax
    import jax.numpy as jnp

    from ...core.op_call import apply as _apply
    from ...tensor.creation import _as_t

    lt, yt = _as_t(logits), _as_t(label)

    def f(lg, y):
        # clip strictly inside (-1, 1): d(arccos)/dx is infinite at ±1 and
        # would NaN the whole gradient row
        eps = 1e-6
        cos = jnp.clip(lg, -1.0 + eps, 1.0 - eps)
        n, c = cos.shape
        onehot = jax.nn.one_hot(y.astype(jnp.int32), c, dtype=cos.dtype)
        theta = jnp.arccos(cos)
        target_cos = jnp.cos(margin1 * theta + margin2) - margin3
        adj = jnp.where(onehot > 0, target_cos, cos) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jax.nn.softmax(adj, axis=-1)
        return loss

    return _apply(f, lt, yt, _op_name="margin_cross_entropy")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def f(mu, y, var):
        v = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(v) + jnp.square(y - mu) / v)
        if full:
            loss = loss + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, loss.dtype))
        return _reduce(loss, reduction)

    return apply(f, _as_t(input), _as_t(label), _as_t(variance),
                 _op_name="gaussian_nll_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            # Stirling approximation term for y > 1
            stir = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            loss = loss + jnp.where(y > 1, stir, 0.0)
        return _reduce(loss, reduction)

    return apply(f, _as_t(input), _as_t(label), _op_name="poisson_nll_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    # softplus(-y*x), computed stably (log1p(exp(z)) overflows for z > ~88)
    return apply(
        lambda x, y: _reduce(jax.nn.softplus(-y * x), reduction),
        _as_t(input), _as_t(label), _op_name="soft_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    args = [_as_t(input), _as_t(label)]
    if weight is not None:
        args.append(_as_t(weight).detach())

    def f(x, y, *w):
        term = y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x)
        if w:
            term = term * w[0]
        loss = -jnp.mean(term, axis=-1)
        return _reduce(loss, reduction)

    return apply(f, *args, _op_name="multi_label_soft_margin_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    args = [_as_t(input), _as_t(label).detach()]
    if weight is not None:
        args.append(_as_t(weight).detach())

    def f(x, y, *w):
        n, c = x.shape
        y = y.astype(jnp.int32).reshape(-1)
        true_score = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(0.0, margin - true_score + x) ** p
        if w:
            m = m * jnp.take(w[0], y)[:, None]
        m = m.at[jnp.arange(n), y].set(0.0)
        return _reduce(jnp.sum(m, axis=1) / c, reduction)

    return apply(f, *args, _op_name="multi_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dsn = distance_function(positive, negative)
        from ...tensor.math import minimum as _min

        dn = _min(dn, dsn)

    def f(a, b):
        return _reduce(jnp.maximum(a - b + margin, 0.0), reduction)

    return apply(f, _as_t(dp), _as_t(dn),
                 _op_name="triplet_margin_with_distance_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """ref dice_loss: input [N, ..., C] probabilities, label [N, ..., 1]."""
    def f(x, y):
        c = x.shape[-1]
        y1 = jax.nn.one_hot(y.astype(jnp.int32).squeeze(-1), c, dtype=x.dtype)
        xf = x.reshape(x.shape[0], -1)
        yf = y1.reshape(y1.shape[0], -1)
        inter = jnp.sum(xf * yf, axis=1)
        union = jnp.sum(xf, axis=1) + jnp.sum(yf, axis=1)
        return jnp.mean(1.0 - (2 * inter + epsilon) / (union + epsilon))

    return apply(f, _as_t(input), _as_t(label).detach(), _op_name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """ref npair_loss (improved deep metric learning)."""
    def f(a, p, y):
        y = y.reshape(-1)
        sim = a @ p.T  # [n, n]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        same = same / jnp.maximum(jnp.sum(same, axis=1, keepdims=True), 1.0)
        ce = jnp.mean(
            jax.scipy.special.logsumexp(sim, axis=1) -
            jnp.sum(sim * same, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(a), 1)) +
                        jnp.mean(jnp.sum(jnp.square(p), 1))) * 0.25
        return ce + reg

    return apply(f, _as_t(anchor), _as_t(positive), _as_t(labels).detach(),
                 _op_name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid with the default complete-binary-tree coding the
    reference uses when no custom path table is given."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "custom path_table/path_code hsigmoid is not supported; use the "
            "default complete-binary-tree coding")
    import numpy as np

    n_inner = int(num_classes) - 1  # inner nodes of the complete tree
    depth = max(int(np.ceil(np.log2(max(num_classes, 2)))), 1)

    # static per-class paths through the tree (host-side, like the
    # reference's prebuilt coding table)
    codes = np.zeros((num_classes, depth), np.int32)   # inner-node index
    signs = np.zeros((num_classes, depth), np.float32)  # +1 left / -1 right
    mask = np.zeros((num_classes, depth), np.float32)
    for cls in range(num_classes):
        node = cls + n_inner  # leaf id in heap order
        lvl = 0
        path = []
        while node > 0 and lvl < depth:
            parent = (node - 1) // 2
            left = node == 2 * parent + 1
            path.append((parent, 1.0 if left else -1.0))
            node = parent
            lvl += 1
        for i, (pn, sgn) in enumerate(reversed(path)):
            codes[cls, i] = pn
            signs[cls, i] = sgn
            mask[cls, i] = 1.0

    args = [_as_t(input), _as_t(label).detach(), _as_t(weight)]
    if bias is not None:
        args.append(_as_t(bias))

    def f(x, y, w, *b):
        y = y.astype(jnp.int32).reshape(-1)
        pc = jnp.asarray(codes)[y]     # [n, depth]
        sg = jnp.asarray(signs)[y]
        mk = jnp.asarray(mask)[y]
        wn = w[pc]                     # [n, depth, d]
        logits = jnp.einsum("nd,nkd->nk", x, wn)
        if b:
            logits = logits + b[0][pc]
        loss = -jax.nn.log_sigmoid(sg * logits) * mk
        return jnp.mean(jnp.sum(loss, axis=1))

    return apply(f, *args, _op_name="hsigmoid_loss")
