"""Pooling via lax.reduce_window (ref: phi pool kernels (U))."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ...core.op_call import apply
from ...tensor.creation import _as_t
from .conv import _norm_tuple, _norm_padding


def _pool(x, kernel, stride, padding, n, data_format, reducer, init, ceil_mode=False, average=False, exclusive=True):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    kernel = _norm_tuple(kernel, n)
    stride = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _norm_padding(padding, n)
    if isinstance(pad, str):
        pad_cfg = pad
    else:
        pad_cfg = pad

    def f(a):
        if channel_last:
            dims = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = [(0, 0)] + (pad_cfg if not isinstance(pad_cfg, str) else []) + [(0, 0)]
        else:
            dims = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = [(0, 0), (0, 0)] + (pad_cfg if not isinstance(pad_cfg, str) else [])
        if isinstance(pad_cfg, str):
            pads = pad_cfg
        out = lax.reduce_window(a, init, reducer, dims, strides, pads)
        if average:
            if exclusive and not isinstance(pads, str) and any(p != (0, 0) for p in pads):
                ones = jnp.ones_like(a)
                counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
                out = out / counts
            else:
                out = out / float(np.prod(kernel))
        return out

    return apply(f, _as_t(x), _op_name=("avg_pool" if average else "max_pool") + f"{n}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, df, lax.max, -jnp.inf, ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, lax.max, -jnp.inf, ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, lax.max, -jnp.inf, ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, df, lax.add, 0.0, ceil_mode, average=True, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, lax.add, 0.0, ceil_mode, average=True, exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, lax.add, 0.0, ceil_mode, average=True, exclusive=exclusive)


def _adaptive_pool(x, output_size, n, data_format, mode):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    out_size = output_size if isinstance(output_size, (list, tuple)) else [output_size] * n
    out_size = [int(s) for s in out_size]

    def f(a):
        spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
        out = a
        for i, (ins, outs) in enumerate(zip(spatial, out_size)):
            ax = (2 + i) if not channel_last else (1 + i)
            if outs is None or outs == ins:
                continue
            # split into outs segments, paddle-style start/end indices
            starts = [(j * ins) // outs for j in range(outs)]
            ends = [-(-((j + 1) * ins) // outs) for j in range(outs)]
            segs = []
            for s, e in zip(starts, ends):
                seg = lax.slice_in_dim(out, s, e, axis=ax)
                if mode == "avg":
                    segs.append(jnp.mean(seg, axis=ax, keepdims=True))
                else:
                    segs.append(jnp.max(seg, axis=ax, keepdims=True))
            out = jnp.concatenate(segs, axis=ax)
        return out

    return apply(f, _as_t(x), _op_name=f"adaptive_{mode}_pool{n}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", "max")
