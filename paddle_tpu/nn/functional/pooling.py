"""Pooling via lax.reduce_window (ref: phi pool kernels (U))."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ...core.op_call import apply
from ...tensor.creation import _as_t
from .conv import _norm_tuple, _norm_padding


def _pool(x, kernel, stride, padding, n, data_format, reducer, init, ceil_mode=False, average=False, exclusive=True):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    kernel = _norm_tuple(kernel, n)
    stride = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _norm_padding(padding, n)
    if isinstance(pad, str):
        pad_cfg = pad
    else:
        pad_cfg = pad

    def f(a):
        spatial_pads = pad_cfg
        out_sp = None
        if not isinstance(pad_cfg, str):
            # Reference ceil_mode: out = ceil((L + pl + pr - k)/s) + 1, then
            # decrement whenever the last window would start entirely inside
            # the right padding ((out-1)*s >= L + pl). Pad to exactly the
            # length those windows need and trim any surplus below.
            spatial = a.shape[1:-1] if channel_last else a.shape[2:]
            spatial_pads = []
            out_sp = []
            for i, (pl, pr) in enumerate(pad_cfg):
                L = spatial[i]
                num = L + pl + pr - kernel[i]
                if ceil_mode:
                    osz = -(-num // stride[i]) + 1
                    if (osz - 1) * stride[i] >= L + pl:
                        osz -= 1
                else:
                    osz = num // stride[i] + 1
                need_pr = (osz - 1) * stride[i] + kernel[i] - L - pl
                spatial_pads.append((pl, max(0, need_pr)))
                out_sp.append(osz)
        if channel_last:
            dims = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = [(0, 0)] + (spatial_pads if not isinstance(spatial_pads, str) else []) + [(0, 0)]
        else:
            dims = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = [(0, 0), (0, 0)] + (spatial_pads if not isinstance(spatial_pads, str) else [])
        if isinstance(pad_cfg, str):
            pads = pad_cfg
        out = lax.reduce_window(a, init, reducer, dims, strides, pads)
        if average:
            if exclusive and not isinstance(pads, str) and any(p != (0, 0) for p in pads):
                ones = jnp.ones_like(a)
                counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
                out = out / counts
            else:
                out = out / float(np.prod(kernel))
        if out_sp is not None:
            for i, osz in enumerate(out_sp):
                ax = (1 + i) if channel_last else (2 + i)
                if out.shape[ax] != osz:
                    out = lax.slice_in_dim(out, 0, osz, axis=ax)
        return out

    return apply(f, _as_t(x), _op_name=("avg_pool" if average else "max_pool") + f"{n}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1,
                                   df == "NWC", ceil_mode)
    return _pool(x, kernel_size, stride, padding, 1, df, lax.max, -jnp.inf, ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2,
                                   data_format == "NHWC", ceil_mode)
    return _pool(x, kernel_size, stride, padding, 2, data_format, lax.max, -jnp.inf, ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3,
                                   data_format == "NDHWC", ceil_mode)
    return _pool(x, kernel_size, stride, padding, 3, data_format, lax.max, -jnp.inf, ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, df, lax.add, 0.0, ceil_mode, average=True, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, lax.add, 0.0, ceil_mode, average=True, exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, lax.add, 0.0, ceil_mode, average=True, exclusive=exclusive)


def _adaptive_pool(x, output_size, n, data_format, mode):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    out_size = output_size if isinstance(output_size, (list, tuple)) else [output_size] * n
    out_size = [int(s) for s in out_size]

    def f(a):
        spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
        out = a
        for i, (ins, outs) in enumerate(zip(spatial, out_size)):
            ax = (2 + i) if not channel_last else (1 + i)
            if outs is None or outs == ins:
                continue
            # split into outs segments, paddle-style start/end indices
            starts = [(j * ins) // outs for j in range(outs)]
            ends = [-(-((j + 1) * ins) // outs) for j in range(outs)]
            segs = []
            for s, e in zip(starts, ends):
                seg = lax.slice_in_dim(out, s, e, axis=ax)
                if mode == "avg":
                    segs.append(jnp.mean(seg, axis=ax, keepdims=True))
                else:
                    segs.append(jnp.max(seg, axis=ax, keepdims=True))
            out = jnp.concatenate(segs, axis=ax)
        return out

    return apply(f, _as_t(x), _op_name=f"adaptive_{mode}_pool{n}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", "max")


def _max_pool_with_mask(x, kernel, stride, padding, n, channel_last,
                        ceil_mode=False):
    """Pooled output + flat argmax indices per (N, C) plane (the reference's
    return_mask=True contract, consumed by max_unpool*d)."""
    kernel = _norm_tuple(kernel, n)
    stride = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _norm_padding(padding, n)
    if isinstance(pad, str):
        raise NotImplementedError("return_mask with string padding")
    pad_lo = tuple(p[0] for p in pad)

    def f(a):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)  # to NC...
        spatial = a.shape[2:]
        def _osz(i):
            num = spatial[i] + pad[i][0] + pad[i][1] - kernel[i]
            if ceil_mode:
                osz = -(-num // stride[i]) + 1
                if (osz - 1) * stride[i] >= spatial[i] + pad[i][0]:
                    osz -= 1
                return osz
            return num // stride[i] + 1
        out_sp = tuple(_osz(i) for i in range(n))
        # coords[d]: [out_d, k_d] input coordinate along dim d
        grids = []
        for d in range(n):
            o = jnp.arange(out_sp[d])[:, None] * stride[d] - pad_lo[d]
            w = jnp.arange(kernel[d])[None, :]
            grids.append(o + w)
        # build gather coords with broadcasting: result [out..., k...]
        coords = []
        for d in range(n):
            sh = [1] * (2 * n)
            sh[d] = out_sp[d]
            sh[n + d] = kernel[d]
            coords.append(grids[d].reshape(sh))
        valid = None
        flat_idx = None
        for d in range(n):
            c = coords[d]
            v = (c >= 0) & (c < spatial[d])
            valid = v if valid is None else (valid & v)
            cc = jnp.clip(c, 0, spatial[d] - 1)
            flat_idx = cc if flat_idx is None else flat_idx * spatial[d] + cc
        flat_idx = jnp.broadcast_to(
            flat_idx, tuple(out_sp) + tuple(kernel)).reshape(-1)
        valid = jnp.broadcast_to(
            valid, tuple(out_sp) + tuple(kernel)).reshape(-1)
        a_flat = a.reshape(a.shape[0], a.shape[1], -1)      # [N, C, prod(sp)]
        gathered = a_flat[:, :, flat_idx]                   # [N, C, L*K]
        gathered = jnp.where(valid[None, None, :], gathered, -jnp.inf)
        L = int(np.prod(out_sp))
        K = int(np.prod(kernel))
        windows = gathered.reshape(a.shape[0], a.shape[1], L, K)
        arg = jnp.argmax(windows, axis=-1)                  # [N, C, L]
        out = jnp.take_along_axis(windows, arg[..., None], -1)[..., 0]
        src = flat_idx.reshape(L, K)
        mask = jnp.take_along_axis(
            jnp.broadcast_to(src, (a.shape[0], a.shape[1], L, K)),
            arg[..., None], -1)[..., 0]
        out = out.reshape(a.shape[:2] + out_sp)
        mask = mask.reshape(a.shape[:2] + out_sp).astype(jnp.int32)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
            mask = jnp.moveaxis(mask, 1, -1)
        return out, mask

    return apply(f, _as_t(x), _op_name=f"max_pool{n}d_mask")


def _max_unpool(x, indices, kernel, stride, padding, n, output_size,
                channel_last):
    kernel = _norm_tuple(kernel, n)
    stride = _norm_tuple(stride if stride is not None else kernel, n)
    pad = _norm_padding(padding, n)
    pad_lo = tuple(p[0] for p in pad) if not isinstance(pad, str) else (0,) * n

    def f(a, idx):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
            idx = jnp.moveaxis(idx, -1, 1)
        in_sp = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(s) for s in output_size[-n:])
        else:
            out_sp = tuple((in_sp[i] - 1) * stride[i] - 2 * pad_lo[i]
                           + kernel[i] for i in range(n))
        N, C = a.shape[:2]
        L = int(np.prod(in_sp))
        M = int(np.prod(out_sp))
        flat = jnp.zeros((N * C, M), a.dtype)
        vals = a.reshape(N * C, L)
        ids = idx.reshape(N * C, L).astype(jnp.int32)
        flat = flat.at[jnp.arange(N * C)[:, None], ids].set(vals)
        out = flat.reshape((N, C) + out_sp)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply(f, _as_t(x), _as_t(indices).detach(),
                 _op_name=f"max_unpool{n}d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 1,
                       output_size, data_format in ("NLC",))


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 2,
                       output_size, data_format in ("NHWC",))


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 3,
                       output_size, data_format in ("NDHWC",))
