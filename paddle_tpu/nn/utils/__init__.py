"""paddle.nn.utils parity (ref: python/paddle/nn/utils/ (U): weight_norm,
spectral_norm hooks, parameters_to_vector, clip_grad_*)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, Parameter
from ...core import tape as _tape
from ...tensor.creation import _as_t


def _norm_except(v, dim):
    # dim=None: reference semantics are a single norm over EVERY axis
    # (scalar g), not per-slice along axis 0
    axes = tuple(i for i in range(v.ndim) if dim is None or i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


class _WeightNormHook:
    def __init__(self, layer, name, dim):
        self.name = name
        self.dim = dim
        w = getattr(layer, name)
        g = Parameter(np.asarray(_norm_except(w._data, dim)))
        v = Parameter(np.asarray(w._data))
        layer.add_parameter(name + "_g", g)
        layer.add_parameter(name + "_v", v)
        # the original weight becomes derived state, not a parameter
        if name in layer._parameters:
            del layer._parameters[name]

    def __call__(self, layer, inputs):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        from ...core.op_call import apply

        w = apply(
            lambda gv, vv: gv * vv / jnp.maximum(
                _norm_except(vv, self.dim), 1e-12),
            g, v, _op_name="weight_norm")
        object.__setattr__(layer, self.name, w)
        return None


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize layer.<name> = g * v / ||v|| (per-slice along `dim`).
    g and v become the trainable parameters; the weight is recomputed on
    every forward (inside jit this folds into the step program)."""
    hook = _WeightNormHook(layer, name, dim)
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = handle
    layer._weight_norm_hook = hook
    hook(layer, ())  # materialize immediately (ref does too)
    return layer


def remove_weight_norm(layer, name="weight"):
    hook = getattr(layer, "_weight_norm_hook", None)
    if hook is None:
        raise ValueError("layer has no weight_norm applied")
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    w = Parameter(np.asarray(
        (g._data * v._data / np.maximum(
            np.asarray(_norm_except(v._data, hook.dim)), 1e-12))))
    layer._weight_norm_handle.remove()
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    layer.add_parameter(name, w)
    del layer._weight_norm_handle
    del layer._weight_norm_hook
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral normalization via power iteration on each forward (state u/v
    kept as layer buffers, matching the reference's running estimates)."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    shape = w.shape
    h = int(shape[dim])

    rng = np.random.RandomState(0)
    u0 = rng.randn(h).astype(np.float32)
    layer._sn_u = u0 / max(np.linalg.norm(u0), eps)
    layer._sn_dim = dim
    layer._sn_name = name
    v_param = Parameter(np.asarray(w._data))
    layer.add_parameter(name + "_orig", v_param)
    if name in layer._parameters:
        del layer._parameters[name]

    def hook(lyr, inputs):
        from ...core.op_call import apply

        worig = getattr(lyr, name + "_orig")

        def f(wv):
            wm = jnp.moveaxis(wv, dim, 0).reshape(h, -1)
            u = jnp.asarray(lyr._sn_u)
            for _ in range(n_power_iterations):
                v = wm.T @ u
                v = v / jnp.maximum(jnp.linalg.norm(v), eps)
                u = wm @ v
                u = u / jnp.maximum(jnp.linalg.norm(u), eps)
            sigma = u @ (wm @ v)
            # persist u so power iteration ACCUMULATES across forwards
            # (the reference's running estimate); only with concrete
            # values — a traced u would leak a tracer out of the program
            import jax as _jax

            if not isinstance(u, _jax.core.Tracer):
                lyr._sn_u = np.asarray(u)
            return wv / sigma

        wn = apply(f, worig, _op_name="spectral_norm")
        object.__setattr__(lyr, name, wn)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_handle = handle
    hook(layer, ())
    return layer


def parameters_to_vector(parameters, name=None):
    from ...tensor.manipulation import concat, reshape

    ps = list(parameters)
    return concat([reshape(p, [-1]) for p in ps], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    vec = _as_t(vec)
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        chunk = vec._data[offset:offset + n].reshape(p.shape)
        p._data = chunk.astype(p._data.dtype)
        offset += n
    return list(parameters)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data) ** norm_type) for g in grads])) \
            ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite gradient norm in clip_grad_norm_")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for g in grads:
        g._data = g._data * scale.astype(g._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)


__all__ = [
    "weight_norm", "remove_weight_norm", "spectral_norm",
    "parameters_to_vector", "vector_to_parameters",
    "clip_grad_norm_", "clip_grad_value_",
]
