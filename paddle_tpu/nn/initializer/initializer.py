"""Weight initializers (ref: python/paddle/nn/initializer/ (U)).

Each initializer is a pure function of (shape, dtype) drawing from the global
key stream — deterministic under paddle.seed, replayable per parallel axis via
the RNG tracker.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core import random_state


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle uses [out_c, in_c, *spatial] (NCHW convention);
    # receptive field multiplies both fans
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError

    def _key(self):
        return random_state.next_key()


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.normal(self._key(), shape, dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=jnp.float32):
        z = jax.random.truncated_normal(self._key(), self.a, self.b, shape, dtype)
        return z * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(self._key(), shape, dtype, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fin, fout = _fans(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        std = self.gain * math.sqrt(2.0 / (fin + fout))
        return jax.random.normal(self._key(), shape, dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fin, fout = _fans(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        limit = self.gain * math.sqrt(6.0 / (fin + fout))
        return jax.random.uniform(self._key(), shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fin, _ = _fans(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fin)
        return jax.random.normal(self._key(), shape, dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fin, _ = _fans(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fin)
        return jax.random.uniform(self._key(), shape, dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        from ...core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v._data
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            idx = (i, i % ic) + tuple(centers)
            out[idx] = 1.0
        return jnp.asarray(out, dtype=dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        return jax.nn.initializers.orthogonal(scale=self.gain)(self._key(), shape, dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a**2))
    if nonlinearity == "selu":
        return 3.0 / 4
    raise ValueError(f"unknown nonlinearity {nonlinearity}")
