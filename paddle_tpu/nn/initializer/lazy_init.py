"""LazyGuard (ref: python/paddle/nn/initializer/lazy_init.py (U)).

The reference defers parameter materialization until `.initialize()` so huge
models can be constructed cheaply on one process. On the TPU build parameter
arrays are committed buffers only when first used by a compiled program (jax
arrays are lazy until consumed), and sharded construction goes through
fleet/auto-parallel shardings — so LazyGuard is a compatibility no-op that
keeps reference construction scripts running unchanged."""

import contextlib


class LazyGuard(contextlib.AbstractContextManager):
    def __exit__(self, *exc):
        return False
