from .initializer import (
    Initializer, Constant, Normal, TruncatedNormal, Uniform, XavierNormal,
    XavierUniform, KaimingNormal, KaimingUniform, Assign, Dirac, Orthogonal,
    calculate_gain,
)


class LazyGuard:
    """paddle.LazyGuard parity: in this framework initialization is already
    lazy-cheap (device arrays materialize on first use), so this is a no-op
    context manager kept for API compatibility."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def set_global_initializer(weight_init, bias_init=None):
    from . import initializer as _m

    _GLOBAL_INIT[0] = weight_init
    _GLOBAL_INIT[1] = bias_init


_GLOBAL_INIT = [None, None]
