"""Gradient clipping (ref: python/paddle/nn/clip.py (U)).

ClipGradByGlobalNorm computes ONE fused global norm over all grads — on TPU
this is a single XLA reduction tree, and under hybrid parallelism the
distributed optimizer extends the norm with a psum across mesh axes
(SURVEY.md §7 hard-parts list).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import tape as _tape


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        out = []
        with _tape.no_grad():
            for p, g in params_grads:
                if g is None:
                    out.append((p, g))
                    continue
                out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        with _tape.no_grad():
            for p, g in params_grads:
                if g is None:
                    out.append((p, g))
                    continue
                norm = jnp.linalg.norm(g._data.reshape(-1))
                scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
                out.append((p, Tensor(g._data * scale)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def __call__(self, params_grads):
        with _tape.no_grad():
            sq = [jnp.sum(jnp.square(g._data.astype(jnp.float32))) for p, g in params_grads if g is not None]
            if not sq:
                return params_grads
            global_sq = sum(sq[1:], sq[0])
            global_sq = self._allreduce_if_distributed(global_sq)
            gnorm = jnp.sqrt(global_sq)
            scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
            out = []
            for p, g in params_grads:
                if g is None:
                    out.append((p, g))
                    continue
                out.append((p, Tensor((g._data.astype(jnp.float32) * scale).astype(g._data.dtype))))
        return out

    def _allreduce_if_distributed(self, global_sq):
        """Under shard_map, sum the squared-norm contribution across model-
        parallel axes so ranks agree on the clip scale (hybrid-parallel
        parity with HybridParallelClipGrad)."""
        from ..distributed.collective_ctx import axes_in_scope, psum_scoped

        for ax in axes_in_scope(("mp", "pp", "sharding", "sep")):
            global_sq = psum_scoped(global_sq, ax)
        return global_sq


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad._data for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(g), norm_type)) for g in grads), 1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = p.grad._data * scale
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
