"""nn.Layer: the module base class.

Reference parity: python/paddle/nn/layer/layers.py (U) — parameters, buffers,
sublayers, hooks, state_dict, train/eval. TPU-native addition: `raw_state()` /
`functional_call()` expose the layer as a pure pytree function so the whole
module tree can be staged into one `jax.jit`/`pjit` program (the role the
reference's dy2static PartialProgramLayer plays, SURVEY.md §3.4).
"""

from __future__ import annotations

import contextlib
from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, Parameter
from ...core.dtype import to_jax_dtype, get_default_dtype

_NAME_COUNTERS = {}


def _unique_name(prefix: str) -> str:
    idx = _NAME_COUNTERS.get(prefix, 0)
    _NAME_COUNTERS[prefix] = idx + 1  # noqa: PTA402 -- str-keyed int counter
    return f"{prefix}_{idx}"


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._full_name = _unique_name(name_scope or self.__class__.__name__.lower())
        self._parameters = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._hook_counter = 0

    # ---------------- construction ----------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, None)
                    return
                raise TypeError(f"cannot assign non-Parameter to parameter {name!r}")
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
                    return
                buffers.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        from ..initializer import Constant, XavierUniform
        from ...framework.param_attr import ParamAttr

        attr = ParamAttr._to_attr(attr)
        dtype = to_jax_dtype(dtype) if dtype else to_jax_dtype(self._dtype)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = Constant(0.0) if is_bias else XavierUniform()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=(attr.name if attr and attr.name else _unique_name("param")))
        if attr is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
            if not attr.trainable:
                p.stop_gradient = True
                p.trainable = False
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return Tensor(jnp.zeros([], to_jax_dtype(dtype) if dtype else get_default_dtype()))

    # ---------------- traversal ----------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self):
        return [l for _, l in self.named_children()]

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # ---------------- modes ----------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # ---------------- hooks ----------------
    def register_forward_pre_hook(self, hook):
        self._hook_counter += 1
        self._forward_pre_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_counter)

    def register_forward_post_hook(self, hook):
        self._hook_counter += 1
        self._forward_post_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_counter)

    # ---------------- execution ----------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # ---------------- state ----------------
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        dest = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            owner = self
            if "." in name:
                for part in name.split(".")[:-1]:
                    owner = owner._sub_layers[part]
            if short in owner._non_persistable_buffer_names:
                continue
            dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            data = value._data if isinstance(value, Tensor) else jnp.asarray(np.asarray(value))
            if tuple(target._data.shape) != tuple(data.shape):
                raise ValueError(
                    f"shape mismatch for {name}: got {tuple(data.shape)}, expected {tuple(target._data.shape)}"
                )
            target._data = data.astype(target._data.dtype)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            jd = to_jax_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._data = p._data.astype(jd)
            for b in self.buffers():
                if b is not None and jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._data = b._data.astype(jd)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ---------------- functional bridge (TPU-native) ----------------
    def raw_state(self):
        """name -> jnp array for every parameter and persistable buffer."""
        return {k: v._data for k, v in self.state_dict().items()}

    @contextlib.contextmanager
    def use_state(self, arrays):
        """Temporarily substitute raw arrays (or tracers, under jit) for this
        layer's parameters/buffers; restores originals on exit."""
        sd = self.state_dict()
        saved = {}
        for k, arr in arrays.items():
            if k in sd:
                saved[k] = sd[k]._data
                sd[k]._data = arr
        try:
            yield sd
        finally:
            for k, old in saved.items():
                sd[k]._data = old

    # ---------------- repr ----------------
    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n".join("  " + l for l in mod_str.split("\n"))
            lines.append(f"({name}): {mod_str.strip() if len(mod_str) < 80 else mod_str.lstrip()}")
        main = self.__class__.__name__
        if not lines:
            return f"{main}({extra})"
        body = "\n".join("  " + l for l in lines)
        return f"{main}({extra}\n{body}\n)"

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._buffers) + list(self._sub_layers)
