"""Loss layers (ref: python/paddle/nn/layer/loss.py (U))."""

from __future__ import annotations

from .layers import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False,
                 axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        self._weight = weight
        self._kw = dict(ignore_index=ignore_index, reduction=reduction, soft_label=soft_label,
                        axis=axis, use_softmax=use_softmax, label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self._weight, **self._kw)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self._weight, self._ignore_index, self._reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self._weight, self._reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction
        self._pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self._weight, self._reduction, self._pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._reduction = reduction
        self._delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self._reduction, self._delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self._reduction = reduction
        self._log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self._reduction, self._log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin, self._reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self._margin, self._reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self._margin, self._reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
        super().__init__()
        self._kw = dict(margin=margin, p=p, epsilon=epsilon, swap=swap, reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, **self._kw)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self._blank = blank
        self._reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths, norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths, self._blank, self._reduction, norm_by_times)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self._kw = dict(full=full, epsilon=epsilon, reduction=reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, **self._kw)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._kw = dict(log_input=log_input, full=full, epsilon=epsilon,
                        reduction=reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, **self._kw)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self._weight,
                                              self._reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._kw = dict(p=p, margin=margin, weight=weight, reduction=reduction)

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, **self._kw)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._kw = dict(distance_function=distance_function, margin=margin,
                        swap=swap, reduction=reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(input, positive, negative,
                                                   **self._kw)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid classifier head (default complete-binary-tree
    coding; weight [num_classes-1, feature_size])."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError("custom-tree HSigmoidLoss")
        self._num_classes = num_classes
        self.weight = self.create_parameter(
            shape=[num_classes - 1, feature_size], attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_classes - 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias)
