"""RNN family via lax.scan (ref: python/paddle/nn/layer/rnn.py (U)).

TPU-native: the whole time loop is one `lax.scan`, so XLA compiles a single
fused loop body instead of the reference's per-timestep cuDNN dispatch.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .layers import Layer
from ..initializer import Uniform
from ...core.op_call import apply
from ...core.tensor import Tensor
from ...tensor.creation import _as_t


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        from ...tensor.creation import full

        return full([b, self.hidden_size], init_value, dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply(f, _as_t(inputs), _as_t(states), self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, _op_name="simple_rnn_cell")
        return h, h

    @property
    def state_shape(self):
        return ((self.hidden_size,),)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = (self.get_initial_states(inputs), self.get_initial_states(inputs))
        h0, c0 = states

        def f(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, fgt, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fgt = jax.nn.sigmoid(fgt)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = fgt * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        out = apply(f, _as_t(inputs), _as_t(h0), _as_t(c0), self.weight_ih, self.weight_hh,
                    self.bias_ih, self.bias_hh, _op_name="lstm_cell")
        h, c = out
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h

        h = apply(f, _as_t(inputs), _as_t(states), self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, _op_name="gru_cell")
        return h, h

    @property
    def state_shape(self):
        return ((self.hidden_size,),)


class RNN(Layer):
    """Run a cell over time with lax.scan (paddle.nn.RNN parity)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import stack, unbind

        steps = unbind(inputs, 0 if self.time_major else 1)
        if self.is_reverse:
            steps = steps[::-1]
        states = initial_states
        outs = []
        for x in steps:
            out, states = self.cell(x, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = stack(outs, 0 if self.time_major else 1)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat

        st_fw, st_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, s_fw = self.rnn_fw(inputs, st_fw)
        out_bw, s_bw = self.rnn_bw(inputs, st_bw)
        return concat([out_fw, out_bw], axis=-1), (s_fw, s_bw)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrent net over lax.scan.

    The scan runs over raw arrays inside one taped op so the whole unrolled
    network is a single XLA while-loop — fast on TPU and differentiable."""

    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, activation=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirectional else 1
        gate_mult = {"LSTM": 4, "GRU": 3}.get(self.MODE, 1)
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._param_names = []
        for layer in range(num_layers):
            for direction_i in range(self.num_directions):
                in_size = input_size if layer == 0 else hidden_size * self.num_directions
                sfx = f"l{layer}" + ("_reverse" if direction_i else "")
                wi = self.create_parameter([gate_mult * hidden_size, in_size], weight_ih_attr, default_initializer=init)
                wh = self.create_parameter([gate_mult * hidden_size, hidden_size], weight_hh_attr, default_initializer=init)
                bi = self.create_parameter([gate_mult * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
                bh = self.create_parameter([gate_mult * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)
                self.add_parameter(f"weight_ih_{sfx}", wi)
                self.add_parameter(f"weight_hh_{sfx}", wh)
                self.add_parameter(f"bias_ih_{sfx}", bi)
                self.add_parameter(f"bias_hh_{sfx}", bh)
                self._param_names.append(sfx)

    def _cell_step(self, mode):
        if mode == "LSTM":
            def step(x, hc, wi, wh, bi, bh):
                h, c = hc
                gates = x @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
                h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
                return h_new, (h_new, c_new)
        elif mode == "GRU":
            def step(x, h, wi, wh, bi, bh):
                gi = x @ wi.T + bi
                gh = h @ wh.T + bh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                c = jnp.tanh(ic + r * hc)
                h_new = (1 - z) * c + z * h
                return h_new, h_new
        else:
            act = jnp.tanh if self.MODE == "RNN_TANH" else jax.nn.relu

            def step(x, h, wi, wh, bi, bh):
                h_new = act(x @ wi.T + bi + h @ wh.T + bh)
                return h_new, h_new

        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.MODE
        is_lstm = mode == "LSTM"
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        step = self._cell_step(mode)
        params = []
        for sfx in self._param_names:
            params += [
                self._parameters[f"weight_ih_{sfx}"],
                self._parameters[f"weight_hh_{sfx}"],
                self._parameters[f"bias_ih_{sfx}"],
                self._parameters[f"bias_hh_{sfx}"],
            ]

        init_arrays = []
        if initial_states is not None:
            if is_lstm:
                init_arrays = [_as_t(initial_states[0]), _as_t(initial_states[1])]
            else:
                init_arrays = [_as_t(initial_states)]

        def run(x, *flat):
            c0_all = None
            if initial_states is not None:
                if is_lstm:
                    h0_all, c0_all = flat[0], flat[1]
                    weights = flat[2:]
                else:
                    h0_all = flat[0]
                    weights = flat[1:]
            else:
                h0_all = None
                weights = flat

            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # -> [T, B, ...]
            b = x.shape[1]
            out = x
            last_h, last_c = [], []
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    idx = (layer * nd + d) * 4
                    wi, wh, bi, bh = weights[idx:idx + 4]
                    state_idx = layer * nd + d
                    if h0_all is not None:
                        h0 = h0_all[state_idx]
                        c0 = c0_all[state_idx] if is_lstm else None
                    else:
                        h0 = jnp.zeros((b, hs), x.dtype)
                        c0 = jnp.zeros((b, hs), x.dtype)
                    carry0 = (h0, c0) if is_lstm else h0
                    seq = jnp.flip(out, 0) if d == 1 else out

                    def scan_fn(carry, xt, _wi=wi, _wh=wh, _bi=bi, _bh=bh):
                        h_out, new_carry = step(xt, carry, _wi, _wh, _bi, _bh)
                        return new_carry, h_out

                    final, ys = lax.scan(scan_fn, carry0, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                    if is_lstm:
                        last_h.append(final[0])
                        last_c.append(final[1])
                    else:
                        last_h.append(final)
                out = jnp.concatenate(dir_outs, axis=-1) if nd == 2 else dir_outs[0]
            outputs = out if time_major else jnp.swapaxes(out, 0, 1)
            h_stack = jnp.stack(last_h, 0)
            if is_lstm:
                return outputs, h_stack, jnp.stack(last_c, 0)
            return outputs, h_stack

        out = apply(run, _as_t(inputs), *init_arrays, *params, _op_name=f"rnn_{mode.lower()}")
        if is_lstm:
            outputs, h, c = out
            return outputs, (h, c)
        outputs, h = out
        return outputs, h


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kw):
        self.MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction, time_major, dropout, **kw)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"
