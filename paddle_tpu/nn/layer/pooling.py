"""Pooling layers (ref: python/paddle/nn/layer/pooling.py (U))."""

from __future__ import annotations

from .layers import Layer
from .. import functional as F


class _Pool(Layer):
    def __init__(self, fn, kernel_size=None, stride=None, padding=0, **kw):
        super().__init__()
        self._fn = fn
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._kw = kw

    def forward(self, x):
        return self._fn(x, self._kernel_size, self._stride, self._padding, **self._kw)


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
        super().__init__(F.max_pool1d, kernel_size, stride, padding,
                         return_mask=return_mask, ceil_mode=ceil_mode)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
                 data_format="NCHW", name=None):
        super().__init__(F.max_pool2d, kernel_size, stride, padding,
                         data_format=data_format, return_mask=return_mask,
                         ceil_mode=ceil_mode)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
                 data_format="NCDHW", name=None):
        super().__init__(F.max_pool3d, kernel_size, stride, padding,
                         data_format=data_format, return_mask=return_mask,
                         ceil_mode=ceil_mode)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
        super().__init__(F.avg_pool1d, kernel_size, stride, padding,
                         exclusive=exclusive, ceil_mode=ceil_mode)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
                 divisor_override=None, data_format="NCHW", name=None):
        super().__init__(F.avg_pool2d, kernel_size, stride, padding,
                         data_format=data_format, exclusive=exclusive,
                         ceil_mode=ceil_mode)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
                 divisor_override=None, data_format="NCDHW", name=None):
        super().__init__(F.avg_pool3d, kernel_size, stride, padding,
                         data_format=data_format, exclusive=exclusive,
                         ceil_mode=ceil_mode)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size)


class _MaxUnPool(Layer):
    def __init__(self, fn, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self._fn = fn
        self._args = (kernel_size, stride, padding)
        self._kw = kw

    def forward(self, x, indices):
        return self._fn(x, indices, *self._args, **self._kw)


class MaxUnPool1D(_MaxUnPool):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__(F.max_unpool1d, kernel_size, stride, padding,
                         data_format=data_format, output_size=output_size)


class MaxUnPool2D(_MaxUnPool):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__(F.max_unpool2d, kernel_size, stride, padding,
                         data_format=data_format, output_size=output_size)


class MaxUnPool3D(_MaxUnPool):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCDHW",
                 output_size=None, name=None):
        super().__init__(F.max_unpool3d, kernel_size, stride, padding,
                         data_format=data_format, output_size=output_size)
