from .layers import Layer
from .common import (
    Identity, Linear, Dropout, Dropout2D, Dropout3D, AlphaDropout, Embedding,
    Flatten, Upsample, UpsamplingNearest2D, UpsamplingBilinear2D, PixelShuffle,
    PixelUnshuffle, ChannelShuffle, Pad1D, Pad2D, Pad3D, ZeroPad2D,
    CosineSimilarity, Bilinear, Unfold, Fold, Unflatten, PairwiseDistance,
)
from .conv import (
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .norm import (
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm, LayerNorm,
    RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .activation import (
    ReLU, ReLU6, GELU, SiLU, Swish, Sigmoid, Tanh, LeakyReLU, PReLU, RReLU,
    ELU, SELU, CELU, Mish, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    Softplus, Softshrink, Softsign, Tanhshrink, ThresholdedReLU, LogSigmoid,
    Softmax, LogSoftmax, Maxout, GLU, Softmax2D,
)
from .pooling import (
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
)
from .loss import (
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, CosineEmbeddingLoss,
    HingeEmbeddingLoss, TripletMarginLoss, CTCLoss, GaussianNLLLoss,
    PoissonNLLLoss, SoftMarginLoss, MultiLabelSoftMarginLoss, MultiMarginLoss,
    TripletMarginWithDistanceLoss, HSigmoidLoss,
)
from .container import Sequential, LayerList, LayerDict, ParameterList
from .transformer import (
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .rnn import (
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN, LSTM, GRU,
)
