"""Norm layers (ref: python/paddle/nn/layer/norm.py (U))."""

from __future__ import annotations

import jax.numpy as jnp

from .layers import Layer
from .. import functional as F
from ..initializer import Constant
from ...core.tensor import Tensor


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter([num_features], attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else "NHWC", use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Under jit+shard_map the mean/var reduction
    rides a psum over the data axis; eagerly (single process) it equals
    BatchNorm (ref: python/paddle/nn/layer/norm.py SyncBatchNorm (U))."""

    def forward(self, x):
        from ...distributed.collective_ctx import current_axis

        axis = current_axis("dp")
        if axis is None or not self.training:
            return super().forward(x)
        from ...distributed import functional_norm

        return functional_norm.sync_batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            self._momentum, self._epsilon, self._data_format, axis,
        )

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                out.weight._data = layer.weight._data
            if layer.bias is not None:
                out.bias._data = layer.bias._data
            out._mean._data = layer._mean._data
            out._variance._data = layer._variance._data
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(self._normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """LLaMA-family norm; the reference exposes it as fused_rms_norm in
    incubate — first-class here (Pallas-fused on TPU via ops.rms_norm)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(self._normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, None, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter([num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)

    def extra_repr(self):
        return f"num_groups={self._num_groups}, num_channels={self._num_channels}"


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter([num_features], attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ..initializer import Normal

        self.weight_u = self.create_parameter([h], default_initializer=Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w], default_initializer=Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.op_call import apply

        dim = self._dim
        eps = self._epsilon
        iters = self._power_iters
        u0, v0 = self.weight_u._data, self.weight_v._data

        def f(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma

        return apply(f, weight if isinstance(weight, Tensor) else Tensor(weight))
