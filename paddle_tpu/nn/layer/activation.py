"""Activation layers (ref: python/paddle/nn/layer/activation.py (U))."""

from __future__ import annotations

from .layers import Layer
from .. import functional as F
from ..initializer import Constant


def _simple(fn_name, **defaults):
    class _Act(Layer):
        def __init__(self, name=None, **kw):
            super().__init__()
            self._kw = {**defaults, **kw}

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kw)

    _Act.__name__ = "".join(p.capitalize() for p in fn_name.split("_"))
    return _Act


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class SiLU(Layer):
    def forward(self, x):
        return F.silu(x)


class Swish(SiLU):
    pass


class Sigmoid(Layer):
    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return F.tanh(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr,
                                            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, self.training)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class SELU(Layer):
    def forward(self, x):
        return F.selu(x)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class Mish(Layer):
    def forward(self, x):
        return F.mish(x)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Hardsigmoid(Layer):
    def forward(self, x):
        return F.hardsigmoid(x)


class Hardswish(Layer):
    def forward(self, x):
        return F.hardswish(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Softsign(Layer):
    def forward(self, x):
        return F.softsign(x)


class Tanhshrink(Layer):
    def forward(self, x):
        return F.tanhshrink(x)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self._threshold, self._value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold, self._value)


class LogSigmoid(Layer):
    def forward(self, x):
        return F.log_sigmoid(x)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW inputs (reference Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        if len(x.shape) != 4:
            raise ValueError("Softmax2D expects a 4-D NCHW tensor")
        return F.softmax(x, axis=1)
