"""Transformer layers (ref: python/paddle/nn/layer/transformer.py (U)).

MultiHeadAttention keeps paddle's API (q/k/v projections, cache tuple for
incremental decode) but computes through F.scaled_dot_product_attention so the
Pallas flash kernel is used on TPU whenever shapes allow.
"""

from __future__ import annotations

import collections

import jax.numpy as jnp

from .layers import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList
from .. import functional as F
from ...core.tensor import Tensor
from ...tensor import manipulation as M


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        # self-attention QKV as ONE [E, 3E] GEMM (r5 BERT shape A/B:
        # +2.8% on BERT-base, numerically identical): weights stay
        # separate in the state_dict and concat in-trace (XLA hoists the
        # concat; grads split through it), so checkpoints and the API
        # are unchanged. Default ON; PADDLE_TPU_FUSE_QKV=0 opts out.
        import os as _os

        self._fuse_qkv = (_os.environ.get("PADDLE_TPU_FUSE_QKV", "1")
                          not in ("0", "false", "off")
                          and kdim == embed_dim and vdim == embed_dim)

    def _split_heads(self, x):
        # [B, S, E] -> [B, S, H, D]
        b, s = x.shape[0], x.shape[1]
        return M.reshape(x, [b, s, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        from ...tensor.creation import zeros

        b = key.shape[0]
        k = zeros([b, 0, self.num_heads, self.head_dim])
        v = zeros([b, 0, self.num_heads, self.head_dim])
        return self.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        # identity check, not None check: the encoder layer passes
        # (src, src, src) explicitly, which is still self-attention
        key = query if key is None else key
        value = query if value is None else value
        self_attn = key is query and value is query
        if self._fuse_qkv and self_attn and cache is None:
            wq, wk, wv = (self.q_proj.weight, self.k_proj.weight,
                          self.v_proj.weight)
            w = M.concat([wq, wk, wv], axis=1)          # [E, 3E]
            bias = None
            if self.q_proj.bias is not None:
                bias = M.concat([self.q_proj.bias, self.k_proj.bias,
                                 self.v_proj.bias], axis=0)
            qkv = F.linear(query, w, bias)
            b, s = qkv.shape[0], qkv.shape[1]
            qkv = M.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            new_cache = None
        else:
            q = self._split_heads(self.q_proj(query))
            if isinstance(cache, self.StaticCache):
                k, v = cache.k, cache.v
                new_cache = cache
            else:
                k = self._split_heads(self.k_proj(key))
                v = self._split_heads(self.v_proj(value))
                if isinstance(cache, self.Cache):
                    k = M.concat([cache.k, k], axis=1)
                    v = M.concat([cache.v, v], axis=1)
                    new_cache = self.Cache(k, v)
                else:
                    new_cache = None

        if attn_mask is not None and not isinstance(attn_mask, Tensor):
            attn_mask = Tensor(attn_mask)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout, training=self.training
        )
        b, s = out.shape[0], out.shape[1]
        out = M.reshape(out, [b, s, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None and new_cache is not None:
            return out, new_cache
        if self.need_weights:
            return out, None
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src, type=MultiHeadAttention.Cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, new_cache = layer(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.norm3 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache, static_cache))

    def gen_cache(self, memory):
        incremental_cache = self.self_attn.gen_cache(memory, type=MultiHeadAttention.Cache)
        static_cache = self.cross_attn.gen_cache(memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental_cache, static_cache


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = layer(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, activation="relu", attn_dropout=None,
                 act_dropout=None, normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout,
                                                activation, attn_dropout, act_dropout,
                                                normalize_before, weight_attr, bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout,
                                                activation, attn_dropout, act_dropout,
                                                normalize_before, weight_attr, bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        """Additive causal mask: 0 on/below diagonal, -inf above."""
        m = jnp.triu(jnp.full((length, length), -1e9, jnp.float32), k=1)
        return Tensor(m)
