"""paddle.nn parity namespace (ref: python/paddle/nn/__init__.py (U))."""

from . import functional
from . import utils
from .decode import BeamSearchDecoder, dynamic_decode
from . import initializer
from .layer import *  # noqa: F401,F403
from .layer import Layer
from .clip import (
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
    clip_grad_norm_, clip_grad_value_,
)
from ..framework.param_attr import ParamAttr


def Parameter(*args, **kwargs):
    from ..core.tensor import Parameter as _P

    return _P(*args, **kwargs)
