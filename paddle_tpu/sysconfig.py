"""paddle.sysconfig parity (ref: python/paddle/sysconfig.py (U))."""

import os


def get_include():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "include")


def get_lib():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
