"""Fused LayerNorm / RMSNorm Pallas kernels.

Reference parity: paddle/phi/kernels/gpu/layer_norm_kernel.cu + fused
bias+residual+LN kernels (SURVEY.md §2.1 N3/N4). TPU-native: one VMEM pass
per row block computing the statistics and the normalized output (saving
mean/rstd for backward); backward fuses dx with the dγ/dβ reduction, which
accumulates across row blocks in f32 scratch over a sequential grid.

All statistics in f32 regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default():
    return jax.default_backend() != "tpu"


def _row_block(n):
    return min(256, n)


# --------------------------------------------------------------- layer_norm

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    y = xhat * w_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(x_ref, w_ref, mean_ref, rstd_ref, g_ref,
                   dx_ref, dw_ref, db_ref, dw_scr, db_scr, *, n_blocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[0].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd

    gw = g * w
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = rstd * (gw - m1 - xhat * m2)
    dx_ref[:] = dx.astype(dx_ref.dtype)

    dw_scr[:] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_scr[:] += jnp.sum(g, axis=0, keepdims=True)

    @pl.when(i == n_blocks - 1)
    def _():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)
        db_ref[:] = db_scr[:].astype(db_ref.dtype)


def _ln_call_fwd(x2, w, b, eps, interpret):
    n, h = x2.shape
    bn = _row_block(n)
    grid = (pl.cdiv(n, bn),)
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            # (n, 1): 2-D keeps XLA/Mosaic layouts aligned (1-D f32 mismatches)
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w.reshape(1, h), b.reshape(1, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm(x, weight, bias, eps=1e-5, interpret=None):
    """LayerNorm over the last dim. x: [..., H]."""
    if interpret is None:
        interpret = _interpret_default()
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    pad = (-n) % _row_block(n)
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    y, _, _ = _ln_call_fwd(x2, weight, bias, eps, interpret)
    return y[:n].reshape(x.shape)


def _ln_vjp_fwd(x, weight, bias, eps, interpret):
    if interpret is None:
        interpret = _interpret_default()
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    pad = (-n) % _row_block(n)
    xp = jnp.pad(x2, ((0, pad), (0, 0))) if pad else x2
    y, mean, rstd = _ln_call_fwd(xp, weight, bias, eps, interpret)
    return y[:n].reshape(x.shape), (xp, weight, mean, rstd, x.shape)


def _ln_vjp_bwd(eps, interpret, saved, g):
    if interpret is None:
        interpret = _interpret_default()
    xp, w, mean, rstd, orig_shape = saved
    h = xp.shape[-1]
    n_pad = xp.shape[0]
    g2 = g.reshape(-1, h)
    n = g2.shape[0]
    if n_pad != n:
        g2 = jnp.pad(g2, ((0, n_pad - n), (0, 0)))
    bn = _row_block(n_pad)
    n_blocks = pl.cdiv(n_pad, bn)
    dx, dw, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, n_blocks=n_blocks),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, h), xp.dtype),
            jax.ShapeDtypeStruct((1, h), w.dtype),
            jax.ShapeDtypeStruct((1, h), w.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((1, h), jnp.float32),
                        pltpu.VMEM((1, h), jnp.float32)],
        interpret=interpret,
    )(xp, w.reshape(1, h), mean, rstd, g2)
    return dx[:n].reshape(orig_shape), dw[0], db[0]


layer_norm.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


# ---------------------------------------------------------------- rms_norm

def _rms_fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y_ref[:] = (x * rstd * w_ref[0].astype(jnp.float32)).astype(y_ref.dtype)
    rstd_ref[:] = rstd


def _rms_bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dw_ref, dw_scr,
                    *, n_blocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_scr[:] = jnp.zeros_like(dw_scr)

    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[0].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = x * rstd
    gw = g * w
    m = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = rstd * (gw - xhat * m)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dw_scr[:] += jnp.sum(g * xhat, axis=0, keepdims=True)

    @pl.when(i == n_blocks - 1)
    def _():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm(x, weight, eps=1e-6, interpret=None):
    y, _ = _rms_fwd_call(x, weight, eps, interpret)
    return y


def _rms_fwd_call(x, weight, eps, interpret):
    if interpret is None:
        interpret = _interpret_default()
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    pad = (-n) % _row_block(n)
    xp = jnp.pad(x2, ((0, pad), (0, 0))) if pad else x2
    bn = _row_block(xp.shape[0])
    y, rstd = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(pl.cdiv(xp.shape[0], bn),),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(xp.shape, x.dtype),
            jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32),
        ],
        interpret=interpret,
    )(xp, weight.reshape(1, h))
    return y[:n].reshape(x.shape), (xp, rstd, x.shape)


def _rms_vjp_fwd(x, weight, eps, interpret):
    y, res = _rms_fwd_call(x, weight, eps, interpret)
    return y, (res, weight)


def _rms_vjp_bwd(eps, interpret, saved, g):
    if interpret is None:
        interpret = _interpret_default()
    (xp, rstd, orig_shape), w = saved
    h = xp.shape[-1]
    n_pad = xp.shape[0]
    g2 = g.reshape(-1, h)
    n = g2.shape[0]
    if n_pad != n:
        g2 = jnp.pad(g2, ((0, n_pad - n), (0, 0)))
    bn = _row_block(n_pad)
    n_blocks = pl.cdiv(n_pad, bn)
    dx, dw = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, n_blocks=n_blocks),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, h), xp.dtype),
            jax.ShapeDtypeStruct((1, h), w.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((1, h), jnp.float32)],
        interpret=interpret,
    )(xp, w.reshape(1, h), rstd, g2)
    return dx[:n].reshape(orig_shape), dw[0]


rms_norm.defvjp(_rms_vjp_fwd, _rms_vjp_bwd)


# --------------------------------------------------------------- group_norm

# one VMEM budget governs both the group-block sizing and the routing
# guard in nn/functional/norm.py (keep them from diverging)
_GN_VMEM_BUDGET = 256 * 1024  # f32 elements per block (~1MB)


def _gn_group_block(g, row):
    """Largest divisor of g whose [gb, row] f32 block stays under the
    budget — bounds every VMEM buffer independent of channel count (the
    UNet up-blocks reach C=2560 after skip concats)."""
    budget = _GN_VMEM_BUDGET
    gb = g
    while gb > 1 and gb * row > budget:
        d = 2
        while gb % d and d <= gb:
            d += 1
        gb //= d
    return gb


def _gn_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    # one row per (sample, group): strictly 2-D blocks — Mosaic's layout
    # engine rejects the 4-D [G, Cg, HW] form (hard Check in layout.h)
    x = x_ref[:].astype(jnp.float32)                    # [gb, Cg*HW]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    y = xhat * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _gn_bwd_kernel(x_ref, w_ref, mean_ref, rstd_ref, g_ref,
                   dx_ref, dwc_ref, dbc_ref):
    # grid = (G/gb, N): samples innermost, so the (j,)-indexed dwc/dbc
    # output blocks are revisited consecutively and accumulate in VMEM
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        dwc_ref[:] = jnp.zeros_like(dwc_ref)
        dbc_ref[:] = jnp.zeros_like(dbc_ref)

    x = x_ref[:].astype(jnp.float32)                    # [gb, Cg*HW]
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd
    gw = g * w
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = rstd * (gw - m1 - xhat * m2)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # per-column accumulators; the Cg*HW -> Cg reduction finishes in XLA
    dwc_ref[:] += g * xhat
    dbc_ref[:] += g


def _gn_prep(x, weight, bias, num_groups):
    n, c = x.shape[0], x.shape[1]
    cg = c // num_groups
    hw = 1
    for s in x.shape[2:]:
        hw *= s
    x2 = x.reshape(n * num_groups, cg * hw)
    wf = weight.astype(jnp.float32)
    bf = bias.astype(jnp.float32)
    w2 = jnp.broadcast_to(wf.reshape(num_groups, cg, 1),
                          (num_groups, cg, hw)).reshape(num_groups, cg * hw)
    b2 = jnp.broadcast_to(bf.reshape(num_groups, cg, 1),
                          (num_groups, cg, hw)).reshape(num_groups, cg * hw)
    return x2, w2, b2, (n, num_groups, cg, hw)


def _gn_call_fwd(x2, w2, b2, dims, eps, interpret):
    n, g, cg, hw = dims
    row = cg * hw
    gb = _gn_group_block(g, row)
    ngb = g // gb
    return pl.pallas_call(
        functools.partial(_gn_fwd_kernel, eps=eps),
        grid=(ngb, n),
        in_specs=[
            pl.BlockSpec((gb, row), lambda j, i: (i * ngb + j, 0)),
            pl.BlockSpec((gb, row), lambda j, i: (j, 0)),
            pl.BlockSpec((gb, row), lambda j, i: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((gb, row), lambda j, i: (i * ngb + j, 0)),
            pl.BlockSpec((gb, 1), lambda j, i: (i * ngb + j, 0)),
            pl.BlockSpec((gb, 1), lambda j, i: (i * ngb + j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n * g, row), x2.dtype),
            jax.ShapeDtypeStruct((n * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((n * g, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w2, b2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def group_norm(x, weight, bias, num_groups, eps=1e-5, interpret=None):
    """Fused GroupNorm over NC* layout (the SD-UNet hot norm; ref: the
    fused GroupNorm CUDA kernels in phi/kernels/fusion (U), SURVEY §2.1 N4).
    Grid is (group-blocks, samples): each step normalizes a block of groups
    for one sample in a single VMEM pass; backward fuses dx with dw/db
    accumulation into consecutively-revisited output blocks."""
    y, _ = _gn_fwd(x, weight, bias, num_groups, eps, interpret)
    return y


def _gn_fwd(x, weight, bias, num_groups, eps, interpret):
    if interpret is None:
        interpret = _interpret_default()
    x2, w2, b2, dims = _gn_prep(x, weight, bias, num_groups)
    y, mean, rstd = _gn_call_fwd(x2, w2, b2, dims, eps, interpret)
    return y.reshape(x.shape), (x2, weight, mean, rstd, dims, x.shape)


def _gn_vjp_fwd(x, weight, bias, num_groups, eps, interpret):
    y, res = _gn_fwd(x, weight, bias, num_groups, eps, interpret)
    return y, res


def _gn_vjp_bwd(num_groups, eps, interpret, saved, gy):
    if interpret is None:
        interpret = _interpret_default()
    x2, weight, mean, rstd, dims, orig_shape = saved
    n, g, cg, hw = dims
    row = cg * hw
    gb = _gn_group_block(g, row)
    ngb = g // gb
    w2 = jnp.broadcast_to(
        weight.astype(jnp.float32).reshape(g, cg, 1),
        (g, cg, hw)).reshape(g, row)
    g2 = gy.reshape(n * g, row)
    dx, dwc, dbc = pl.pallas_call(
        _gn_bwd_kernel,
        grid=(ngb, n),
        in_specs=[
            pl.BlockSpec((gb, row), lambda j, i: (i * ngb + j, 0)),
            pl.BlockSpec((gb, row), lambda j, i: (j, 0)),
            pl.BlockSpec((gb, 1), lambda j, i: (i * ngb + j, 0)),
            pl.BlockSpec((gb, 1), lambda j, i: (i * ngb + j, 0)),
            pl.BlockSpec((gb, row), lambda j, i: (i * ngb + j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((gb, row), lambda j, i: (i * ngb + j, 0)),
            pl.BlockSpec((gb, row), lambda j, i: (j, 0)),
            pl.BlockSpec((gb, row), lambda j, i: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n * g, row), x2.dtype),
            jax.ShapeDtypeStruct((g, row), jnp.float32),
            jax.ShapeDtypeStruct((g, row), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w2, mean, rstd, g2)
    dw = dwc.reshape(g, cg, hw).sum(-1).reshape(-1).astype(weight.dtype)
    db = dbc.reshape(g, cg, hw).sum(-1).reshape(-1).astype(weight.dtype)
    return dx.reshape(orig_shape), dw, db


group_norm.defvjp(_gn_vjp_fwd, _gn_vjp_bwd)


def group_norm_supported(x_shape, num_groups):
    """True when channels split evenly into groups and a single group row
    fits the per-block VMEM budget (group-blocking handles everything
    above that)."""
    if len(x_shape) < 3 or x_shape[1] % num_groups:
        return False
    row = x_shape[1] // num_groups
    for s in x_shape[2:]:
        row *= s
    return row <= _GN_VMEM_BUDGET
