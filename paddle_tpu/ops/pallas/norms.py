"""Fused LayerNorm / RMSNorm Pallas kernels.

Reference parity: paddle/phi/kernels/gpu/layer_norm_kernel.cu + fused
bias+residual+LN kernels (SURVEY.md §2.1 N3/N4). TPU-native: one VMEM pass
per row block computing the statistics and the normalized output (saving
mean/rstd for backward); backward fuses dx with the dγ/dβ reduction, which
accumulates across row blocks in f32 scratch over a sequential grid.

All statistics in f32 regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default():
    return jax.default_backend() != "tpu"


def _row_block(n):
    return min(256, n)


# --------------------------------------------------------------- layer_norm

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    y = xhat * w_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(x_ref, w_ref, mean_ref, rstd_ref, g_ref,
                   dx_ref, dw_ref, db_ref, dw_scr, db_scr, *, n_blocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[0].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd

    gw = g * w
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = rstd * (gw - m1 - xhat * m2)
    dx_ref[:] = dx.astype(dx_ref.dtype)

    dw_scr[:] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_scr[:] += jnp.sum(g, axis=0, keepdims=True)

    @pl.when(i == n_blocks - 1)
    def _():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)
        db_ref[:] = db_scr[:].astype(db_ref.dtype)


def _ln_call_fwd(x2, w, b, eps, interpret):
    n, h = x2.shape
    bn = _row_block(n)
    grid = (pl.cdiv(n, bn),)
    return pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            # (n, 1): 2-D keeps XLA/Mosaic layouts aligned (1-D f32 mismatches)
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w.reshape(1, h), b.reshape(1, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm(x, weight, bias, eps=1e-5, interpret=None):
    """LayerNorm over the last dim. x: [..., H]."""
    if interpret is None:
        interpret = _interpret_default()
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    pad = (-n) % _row_block(n)
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    y, _, _ = _ln_call_fwd(x2, weight, bias, eps, interpret)
    return y[:n].reshape(x.shape)


def _ln_vjp_fwd(x, weight, bias, eps, interpret):
    if interpret is None:
        interpret = _interpret_default()
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    pad = (-n) % _row_block(n)
    xp = jnp.pad(x2, ((0, pad), (0, 0))) if pad else x2
    y, mean, rstd = _ln_call_fwd(xp, weight, bias, eps, interpret)
    return y[:n].reshape(x.shape), (xp, weight, mean, rstd, x.shape)


def _ln_vjp_bwd(eps, interpret, saved, g):
    if interpret is None:
        interpret = _interpret_default()
    xp, w, mean, rstd, orig_shape = saved
    h = xp.shape[-1]
    n_pad = xp.shape[0]
    g2 = g.reshape(-1, h)
    n = g2.shape[0]
    if n_pad != n:
        g2 = jnp.pad(g2, ((0, n_pad - n), (0, 0)))
    bn = _row_block(n_pad)
    n_blocks = pl.cdiv(n_pad, bn)
    dx, dw, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, n_blocks=n_blocks),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, h), xp.dtype),
            jax.ShapeDtypeStruct((1, h), w.dtype),
            jax.ShapeDtypeStruct((1, h), w.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((1, h), jnp.float32),
                        pltpu.VMEM((1, h), jnp.float32)],
        interpret=interpret,
    )(xp, w.reshape(1, h), mean, rstd, g2)
    return dx[:n].reshape(orig_shape), dw[0], db[0]


layer_norm.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


# ---------------------------------------------------------------- rms_norm

def _rms_fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y_ref[:] = (x * rstd * w_ref[0].astype(jnp.float32)).astype(y_ref.dtype)
    rstd_ref[:] = rstd


def _rms_bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dw_ref, dw_scr,
                    *, n_blocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_scr[:] = jnp.zeros_like(dw_scr)

    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[0].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = x * rstd
    gw = g * w
    m = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = rstd * (gw - xhat * m)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dw_scr[:] += jnp.sum(g * xhat, axis=0, keepdims=True)

    @pl.when(i == n_blocks - 1)
    def _():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm(x, weight, eps=1e-6, interpret=None):
    y, _ = _rms_fwd_call(x, weight, eps, interpret)
    return y


def _rms_fwd_call(x, weight, eps, interpret):
    if interpret is None:
        interpret = _interpret_default()
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    pad = (-n) % _row_block(n)
    xp = jnp.pad(x2, ((0, pad), (0, 0))) if pad else x2
    bn = _row_block(xp.shape[0])
    y, rstd = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(pl.cdiv(xp.shape[0], bn),),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(xp.shape, x.dtype),
            jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.float32),
        ],
        interpret=interpret,
    )(xp, weight.reshape(1, h))
    return y[:n].reshape(x.shape), (xp, rstd, x.shape)


def _rms_vjp_fwd(x, weight, eps, interpret):
    y, res = _rms_fwd_call(x, weight, eps, interpret)
    return y, (res, weight)


def _rms_vjp_bwd(eps, interpret, saved, g):
    if interpret is None:
        interpret = _interpret_default()
    (xp, rstd, orig_shape), w = saved
    h = xp.shape[-1]
    n_pad = xp.shape[0]
    g2 = g.reshape(-1, h)
    n = g2.shape[0]
    if n_pad != n:
        g2 = jnp.pad(g2, ((0, n_pad - n), (0, 0)))
    bn = _row_block(n_pad)
    n_blocks = pl.cdiv(n_pad, bn)
    dx, dw = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, n_blocks=n_blocks),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, h), xp.dtype),
            jax.ShapeDtypeStruct((1, h), w.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((1, h), jnp.float32)],
        interpret=interpret,
    )(xp, w.reshape(1, h), rstd, g2)
    return dx[:n].reshape(orig_shape), dw[0]


rms_norm.defvjp(_rms_vjp_fwd, _rms_vjp_bwd)
