"""Flash attention as Pallas TPU kernels.

Reference parity: the vendored FlashAttention-2 CUDA library + glue
(paddle/phi/kernels/gpu/flash_attn_kernel.cu, third_party/flashattn — SURVEY.md
§2.1 N5). TPU-native design, not a port: blockwise online-softmax tiled for
VMEM/MXU — grid (batch·heads, q-blocks, k-blocks) with the k dimension
innermost so the output block is revisited and accumulated in f32 scratch;
backward is the recompute form (saved logsumexp only) split into a dq kernel
and a dk/dv kernel so each has a clean accumulation axis.

Layout [B, S, H, D] (the reference flash-attn API layout); internally
[B·H, S, D]. f32 accumulation everywhere; bf16/f16 inputs stay low-precision
on the MXU operands only.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _mask(s, causal, kv_len, q_len, i_q, j_k, bq, bk):
    """Causal and/or key-padding mask for one (bq, bk) score tile. kv_len /
    q_len are the TRUE lengths (static) — padded key columns never attend,
    and the causal diagonal carries the kv_len - q_len offset so a short
    query block (cached decode / chunked prefill) attends to the whole
    prefix, matching the XLA fallback's tril(k=sk-sq)."""
    qi = i_q * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kj = j_k * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = None
    if causal:
        keep = qi + (kv_len - q_len) >= kj
    if kv_len % bk != 0:
        pad_keep = kj < kv_len
        keep = pad_keep if keep is None else (keep & pad_keep)
    if keep is None:
        return s
    return jnp.where(keep, s, NEG_INF)


def _block_sizes(sq, sk, d):
    """Large blocks: TPU grid cells run sequentially on the scalar core, so
    per-cell overhead (~1µs) dominates with small tiles. VMEM budget
    (~16MB/core, minus double-buffering) fits 512×512 f32 score tiles with
    d≤256 comfortably; fall back to smaller tiles for short sequences."""
    rounded_q = -(-sq // 128) * 128  # pad target: next multiple of 128
    rounded_k = -(-sk // 128) * 128
    bq = min(512, rounded_q)
    bk = min(512, rounded_k)
    # score tile (bq×bk f32) + p tile + q/k/v/acc blocks, ×2 for pipelining
    while (2 * bq * bk * 4 + (bq + 2 * bk) * d * 2 * 2 + bq * d * 4) > 8 * 2**20:
        if bk >= bq and bk > 128:
            bk //= 2
        elif bq > 128:
            bq //= 2
        else:
            break
    return bq, bk


def _interpret_default():
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------------ forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, bq, bk, n_k, kv_len, q_len):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def body():
        q = q_ref[0]  # (bq, d)
        k = k_ref[0]  # (bk, d)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask(s, causal, kv_len, q_len, i, j, bq, bk)

        m_prev = m_scr[:]                      # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # k-blocks entirely above the diagonal contribute nothing — skip
        # their MXU/VPU work (the DMA still runs; compute dominates)
        pl.when(j * bk <= (i + 1) * bq - 1 + (kv_len - q_len))(body)
    else:
        body()

    @pl.when(j == n_k - 1)
    def _():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _flash_fwd(q, k, v, scale, causal, interpret, kv_len=None, q_per_kv=1,
               q_len=None):
    """q [BH, sq, d]; k/v [BH // q_per_kv, sk, d] — grouped-query attention
    reads each kv head from q_per_kv query heads without materializing the
    repeat (the reference repeats kv in HBM; here the BlockSpec index map
    does the sharing)."""
    bh, sq, d = q.shape
    kv_len = k.shape[1] if kv_len is None else kv_len
    q_len = sq if q_len is None else q_len
    sk = k.shape[1]
    bq, bk = _block_sizes(sq, sk, d)
    n_q, n_k = pl.cdiv(sq, bq), pl.cdiv(sk, bk)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, n_k=n_k, kv_len=kv_len,
                               q_len=q_len)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda h, i, j: (h // q_per_kv, j, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda h, i, j: (h // q_per_kv, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, i, j: (h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            # (bh, sq, 1): trailing unit dim keeps the block TPU-tileable
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ------------------------------------------------------------------ backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, causal, bq, bk, n_k, kv_len, q_len):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask(s, causal, kv_len, q_len, i, j, bq, bk)

        p = jnp.exp(s - lse_ref[0])                          # (bq, bk)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        pl.when(j * bk <= (i + 1) * bq - 1 + (kv_len - q_len))(body)
    else:
        body()

    @pl.when(j == n_k - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, bq, bk,
                n_q, kv_len, q_len, q_per_kv):
    # grid (bh_kv, n_k, q_per_kv, n_q): the dk/dv block for one kv head sums
    # contributions from its q_per_kv query heads (GQA) and all q blocks
    jb = pl.program_id(1)  # k-block index
    r = pl.program_id(2)   # query-head-within-group index
    i = pl.program_id(3)   # q-block index (innermost: accumulation axis)

    @pl.when((r == 0) & (i == 0))
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def body():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _mask(s, causal, kv_len, q_len, i, jb, bq, bk)

        p = jnp.exp(s - lse_ref[0])                          # (bq, bk)
        do = do_ref[0]
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] += jax.lax.dot_general(ds.astype(q.dtype), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        pl.when(jb * bk <= (i + 1) * bq - 1 + (kv_len - q_len))(body)
    else:
        body()

    @pl.when((r == q_per_kv - 1) & (i == n_q - 1))
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, scale, causal, interpret, kv_len=None,
               q_per_kv=1, q_len=None, delta=None):
    bh, sq, d = q.shape
    bh_kv = k.shape[0]
    kv_len = k.shape[1] if kv_len is None else kv_len
    q_len = sq if q_len is None else q_len
    sk = k.shape[1]
    bq, bk = _block_sizes(sq, sk, d)
    n_q, n_k = pl.cdiv(sq, bq), pl.cdiv(sk, bk)

    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)  # (bh, sq, 1)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, n_k=n_k, kv_len=kv_len,
                          q_len=q_len),
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bk, d), lambda h, i, j: (h // q_per_kv, j, 0)),
            pl.BlockSpec((1, bk, d), lambda h, i, j: (h // q_per_kv, j, 0)),
            pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, i, j: (h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # grid (bh_kv, n_k, q_per_kv, n_q): the (hk, jb) output block stays
    # resident across the two inner dims, so GQA contributions accumulate
    # contiguously in scratch
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, n_q=n_q, kv_len=kv_len,
                          q_len=q_len, q_per_kv=q_per_kv),
        grid=(bh_kv, n_k, q_per_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, bq, d),
                         lambda hk, j, r, i: (hk * q_per_kv + r, i, 0)),
            pl.BlockSpec((1, bk, d), lambda hk, j, r, i: (hk, j, 0)),
            pl.BlockSpec((1, bk, d), lambda hk, j, r, i: (hk, j, 0)),
            pl.BlockSpec((1, bq, d),
                         lambda hk, j, r, i: (hk * q_per_kv + r, i, 0)),
            pl.BlockSpec((1, bq, 1),
                         lambda hk, j, r, i: (hk * q_per_kv + r, i, 0)),
            pl.BlockSpec((1, bq, 1),
                         lambda hk, j, r, i: (hk * q_per_kv + r, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda hk, j, r, i: (hk, j, 0)),
            pl.BlockSpec((1, bk, d), lambda hk, j, r, i: (hk, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh_kv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh_kv, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------ public

def _pad_seq(x, block):
    s = x.shape[1]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_bhsd(q, k, v, scale, causal, interpret):
    """[B·H, S, D] flash attention; k/v may carry fewer heads
    ([B·Hkv, S, D] with H % Hkv == 0) for native grouped-query attention.
    Padded internally to block multiples (padded keys are masked out via an
    explicit key-length guard)."""
    out, _ = _fa_fwd_padded(q, k, v, scale, causal, interpret)
    return out


def _fa_fwd_padded(q, k, v, scale, causal, interpret):
    sq, sk = q.shape[1], k.shape[1]
    q_per_kv = q.shape[0] // k.shape[0]
    bq, bk = _block_sizes(sq, sk, q.shape[2])
    qp, _ = _pad_seq(q, bq)
    kp, _ = _pad_seq(k, bk)
    vp, _ = _pad_seq(v, bk)
    out, lse = _flash_fwd(qp, kp, vp, scale, causal, interpret, kv_len=sk,
                          q_per_kv=q_per_kv, q_len=sq)
    return out[:, :sq], (qp, kp, vp, out, lse)


def _fa_vjp_fwd(q, k, v, scale, causal, interpret):
    out, res = _fa_fwd_padded(q, k, v, scale, causal, interpret)
    return out, (res, q.shape[1], k.shape[1])


def _fa_vjp_bwd(scale, causal, interpret, saved, g):
    (qp, kp, vp, outp, lse), sq, sk = saved
    gp = jnp.pad(g, ((0, 0), (0, qp.shape[1] - sq), (0, 0)))
    dq, dk, dv = _flash_bwd(qp, kp, vp, outp, lse, gp, scale, causal,
                            interpret, kv_len=sk,
                            q_per_kv=qp.shape[0] // kp.shape[0], q_len=sq)
    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


flash_attention_bhsd.defvjp(_fa_vjp_fwd, _fa_vjp_bwd)


def flash_attention(q, k, v, causal=False, scale=None, interpret=None):
    """[B, S, H, D] (reference flash-attn layout) Pallas flash attention.
    k/v may have fewer heads (GQA): [B, S, Hkv, D] with H % Hkv == 0."""
    if interpret is None:
        interpret = _interpret_default()
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"GQA needs q heads {h} divisible by kv heads {hkv}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    def to_bhsd(x):
        hx = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(b * hx, x.shape[1], d)

    qf, kf, vf = to_bhsd(q), to_bhsd(k), to_bhsd(v)
    out = flash_attention_bhsd(qf, kf, vf, float(scale), bool(causal),
                               bool(interpret))
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
