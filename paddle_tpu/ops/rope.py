"""Rotary position embedding (ref: fused_rope kernel,
paddle/phi/kernels/fusion/gpu/fused_rope* (U)).

Pure-jnp expression — XLA fuses the sin/cos generation + rotate into the
surrounding attention matmuls, which is exactly what the reference's fused
CUDA kernel hand-writes. Layout [batch, seq, heads, head_dim].
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_call import apply
from ..core.tensor import Tensor
from ..tensor.creation import _as_t


def _sin_cos(seq_len, head_dim, base, dtype, position_ids=None):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if position_ids is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
        freqs = jnp.outer(t, inv_freq)  # [S, D/2]
    else:
        freqs = position_ids.astype(jnp.float32)[..., None] * inv_freq  # [..., S, D/2]
    return jnp.sin(freqs).astype(dtype), jnp.cos(freqs).astype(dtype)


def rope_arrays(x, sin=None, cos=None, position_ids=None, neox=True, base=10000.0):
    b, s, h, d = x.shape
    if sin is None or cos is None:
        sin, cos = _sin_cos(s, d, base, jnp.float32, position_ids)
    else:
        # accept paddle-style [1, S, 1, D] or [S, D/2]
        sin = jnp.squeeze(sin)
        cos = jnp.squeeze(cos)
        if sin.shape[-1] == d:  # full-dim tables: take the half-table
            sin = sin[..., : d // 2]
            cos = cos[..., : d // 2]

    def to_bs1d(t):
        # normalize to [B or 1, S, 1, D/2] (head axis broadcast)
        if t.ndim == 2:  # [S, D/2]
            return t[None, :, None, :]
        if t.ndim == 3:  # [B, S, D/2] (per-batch position_ids)
            return t[:, :, None, :]
        return t

    sin = to_bs1d(sin)
    cos = to_bs1d(cos)
    xf = x.astype(jnp.float32)
    if neox:
        x1 = xf[..., : d // 2]
        x2 = xf[..., d // 2:]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out = jnp.concatenate([o1, o2], axis=-1)
    else:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out = jnp.stack([o1, o2], axis=-1).reshape(xf.shape)
    return out.astype(x.dtype)


def apply_rotary_emb(x, sin=None, cos=None, position_ids=None, neox=True, base=10000.0):
    x = _as_t(x)
    sin_a = sin._data if isinstance(sin, Tensor) else sin
    cos_a = cos._data if isinstance(cos, Tensor) else cos
    pos_a = position_ids._data if isinstance(position_ids, Tensor) else position_ids

    def f(a):
        return rope_arrays(a, sin_a, cos_a, pos_a, neox, base)

    return apply(f, x, _op_name="fused_rope")
