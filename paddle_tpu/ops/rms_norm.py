"""Fused RMSNorm op (ref: fused_rms_norm CUDA kernel in
paddle/phi/kernels/fusion/gpu (U)). XLA fuses the jnp path into one kernel;
the Pallas tiled variant (ops/pallas/norms.py) takes over on TPU for long
rows where explicit VMEM tiling wins."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_call import apply
from ..tensor.creation import _as_t


def rms_norm_arrays(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1):
    ax = begin_norm_axis % x.ndim
    if (jax.default_backend() == "tpu" and weight is not None and bias is None
            and ax == x.ndim - 1 and weight.ndim == 1):
        from .pallas.norms import rms_norm as pallas_rms

        return pallas_rms(x, weight, epsilon, interpret=False)
    axes = tuple(range(ax, x.ndim))
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=axes, keepdims=True)
    out = (xf * jax.lax.rsqrt(ms + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1):
    args = [_as_t(x)]
    if weight is not None:
        args.append(_as_t(weight))
    if bias is not None:
        args.append(_as_t(bias))

    def f(a, *wb):
        i = 0
        w = b = None
        if weight is not None:
            w = wb[i]
            i += 1
        if bias is not None:
            b = wb[i]
        return rms_norm_arrays(a, w, b, epsilon, begin_norm_axis)

    return apply(f, *args, _op_name="rms_norm")
