"""Flash attention for TPU.

Reference parity: the vendored FlashAttention-2 CUDA library behind
paddle.nn.functional.flash_attention (SURVEY.md §2.1 N5). TPU-native design:
a Pallas blockwise-softmax kernel (ops/pallas/flash.py, arriving with the
kernel layer) with this XLA fallback — jnp einsum + online-softmax-equivalent
math that XLA already fuses well on the MXU. Layout [B, S, H, D], matching the
reference's flash-attn API.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..core.op_call import apply
from ..core.tensor import Tensor
from ..tensor.creation import _as_t


def expand_kv_heads(q, k, v):
    """GQA fallback for XLA paths: materialize the kv-head repeat so einsum
    sees matching head counts (the Pallas kernel shares heads natively)."""
    if k.shape[2] != q.shape[2]:
        if q.shape[2] % k.shape[2]:
            raise ValueError(
                f"GQA needs q heads {q.shape[2]} divisible by kv heads "
                f"{k.shape[2]}")
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def _xla_flash(q, k, v, causal, scale):
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    k, v = expand_kv_heads(q, k, v)
    logits = jnp.einsum("bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v)


def flash_attention_arrays(q, k, v, causal=False, scale=None):
    """Array-level entry used by both the Tensor wrapper and jitted models.

    Routes to the Pallas TPU kernel when available, else the XLA path.
    Head dims that aren't lane-aligned (the SD-UNet's 40/80/160) are
    zero-padded to the next multiple of 128: a sub-128 contraction costs a
    full systolic pass on the MXU anyway, so the padding is compute-free,
    the zeros contribute nothing to q·k, and the padded v columns slice
    off — while the kernel keeps the [s, s] score tile out of HBM (the
    XLA path materializes it)."""
    d = q.shape[-1]
    if jax.default_backend() == "tpu" and d <= 256:
        from .pallas.flash import flash_attention as pallas_flash

        if d % 128:
            dp = -(-d // 128) * 128
            s = scale if scale is not None else 1.0 / math.sqrt(d)
            pad = [(0, 0)] * 3 + [(0, dp - d)]
            out = pallas_flash(jnp.pad(q, pad), jnp.pad(k, pad),
                               jnp.pad(v, pad), causal=causal, scale=s,
                               interpret=False)
            return out[..., :d]
        return pallas_flash(q, k, v, causal=causal, scale=scale,
                            interpret=False)
    return _xla_flash(q, k, v, causal, scale)


def flash_attention(query, key, value, causal=False, scale=None):
    q, k, v = _as_t(query), _as_t(key), _as_t(value)
    return apply(
        functools.partial(flash_attention_arrays, causal=causal, scale=scale),
        q, k, v, _op_name="flash_attention",
    )
