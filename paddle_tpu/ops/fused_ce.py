"""Chunked fused linear + softmax cross-entropy over the vocabulary.

Reference parity: the fused/parallel softmax-CE family
(c_softmax_with_cross_entropy, fused CE in PaddleNLP's LM heads — SURVEY.md
§2.1 N14 / §7.4 "sharded/fused softmax-CE"). TPU-native design: the LM-head
matmul and the CE reduction are evaluated per row-chunk inside a
`lax.scan`, with `jax.checkpoint` on the chunk body, so the full
[batch*seq, vocab] f32 logits tensor never exists — neither in the forward
(only one [chunk, vocab] tile is live at a time) nor as saved residuals for
the backward (the chunk is recomputed during the gradient pass, and grads
w.r.t. hidden states / lm-head weight accumulate across scan ticks via the
scan transpose).

Why this matters on TPU: for the flagship bench (b16 x s1024, V=32k) the
f32 logits are 16384*32000*4 B = 2.0 GiB of HBM traffic each way; chunking
caps that at chunk_rows*V*4 (256 MiB at the default 2048 rows) while the
per-chunk [2048, H] x [H, 32000] matmuls stay large enough to saturate the
MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _pick_chunk_rows(n_rows, requested):
    """Largest divisor of n_rows that is <= requested (falls back to padding
    when n_rows is prime-ish and tiny divisors would shrink the matmul)."""
    c = min(requested, n_rows)
    while n_rows % c != 0:
        c -= 1
    # don't let a pathological divisor (e.g. 1) kill MXU utilisation; the
    # caller pads instead when the best divisor is under half the request
    if c < requested // 2 and n_rows > requested:
        return None
    return c


def fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                               transpose_weight=False, chunk_rows=2048,
                               reduction="mean"):
    """CE(softmax(hidden @ weight), labels) without materialising the logits.

    hidden: [N, H] (any float dtype; matmul accumulates in f32)
    weight: [H, V] (or [V, H] with transpose_weight=True, the tied-embedding
            layout)
    labels: [N] int; rows whose label == ignore_index contribute 0 loss
    reduction: 'mean' (over valid rows) | 'sum' | 'none' is not supported —
            per-row losses would defeat the point of not materialising
            row-major intermediates at full width, use nn.functional.
            cross_entropy for that.
    """
    if reduction not in ("mean", "sum"):
        raise ValueError(
            "fused_linear_cross_entropy supports reduction='mean'|'sum'; "
            "use nn.functional.cross_entropy for per-row losses")
    if transpose_weight:
        h_dim, v_dim = weight.shape[1], weight.shape[0]
    else:
        h_dim, v_dim = weight.shape[0], weight.shape[1]
    n = hidden.shape[0]
    labels = labels.astype(jnp.int32)
    if n == 0:  # empty batch: defined result, matching the unfused path
        return jnp.float32(0.0)

    c = _pick_chunk_rows(n, chunk_rows)
    if c is None:  # pad to a multiple of chunk_rows with ignored rows
        c = min(chunk_rows, n)
        pad = (-n) % c
        hidden = jnp.pad(hidden, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=ignore_index)
        n = n + pad
    n_chunks = n // c

    hs = hidden.reshape(n_chunks, c, h_dim)
    ys = labels.reshape(n_chunks, c)

    def chunk_body(carry, xy):
        h_c, y_c = xy
        if transpose_weight:
            logits = jnp.dot(h_c, weight.T,
                             preferred_element_type=jnp.float32)
        else:
            logits = jnp.dot(h_c, weight,
                             preferred_element_type=jnp.float32)
        # online-softmax-style stable CE on the [c, V] tile
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        valid = y_c != ignore_index
        safe = jnp.where(valid, y_c, 0)
        true_logit = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        loss_sum = jnp.sum(jnp.where(valid, lse - true_logit, 0.0))
        cnt = jnp.sum(valid.astype(jnp.float32))
        tl, tc = carry
        return (tl + loss_sum, tc + cnt), None

    (total, cnt), _ = lax.scan(jax.checkpoint(chunk_body),
                               (jnp.float32(0.0), jnp.float32(0.0)), (hs, ys))
    if reduction == "sum":
        return total
    return total / jnp.maximum(cnt, 1.0)
