"""paddle_tpu.ops: the native-kernel layer (TPU counterpart of the reference's
fused CUDA kernels, SURVEY.md §2.1 N4/N5). Pallas kernels live in
ops/pallas/; each op exposes an array-level function plus a Tensor wrapper."""

from .flash_attention import flash_attention, flash_attention_arrays
