"""Fused masked softmax (ref: paddle.incubate.softmax_mask_fuse /
softmax_mask_fuse_upper_triangle over fused CUDA kernels (U)). One jnp
expression — XLA emits a single fused kernel."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_call import apply
from ..tensor.creation import _as_t


def softmax_mask_fuse(x, mask, name=None):
    return apply(lambda a, m: jax.nn.softmax(a + m, axis=-1), _as_t(x), _as_t(mask).detach(),
                 _op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x):
    def f(a):
        s_q, s_k = a.shape[-2], a.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        return jax.nn.softmax(jnp.where(causal, a, -1e30), axis=-1)

    return apply(f, _as_t(x), _op_name="softmax_mask_fuse_upper_triangle")
