"""paddle.autograd parity (ref: python/paddle/autograd/ (U)): backward,
PyLayer custom autograd, hooks. PyLayer ≡ custom forward + custom vjp recorded
on the tape."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import tape as _tape
from ..core.autograd_engine import backward as _backward_one, grad
from ..core.tape import no_grad, enable_grad, is_grad_enabled, set_grad_enabled


def backward(tensors, grad_tensors=None, retain_graph=False):
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    for t, g in zip(tensors, grad_tensors):
        _backward_one(t, grad_tensor=g, retain_graph=retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return self._saved

    saved_tensors = property(lambda self: self._saved)

    def mark_not_inplace(self, *args):
        self.not_inplace_tensors = args

    def set_materialize_grads(self, value):
        self._materialize = value


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op (ref: paddle.autograd.PyLayer).

    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x): ctx.save_for_backward(x); return x**3
        @staticmethod
        def backward(ctx, dy): (x,) = ctx.saved_tensor(); return dy * 3 * x**2
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        import jax as _jax

        in_tensors = [a for a in args if isinstance(a, Tensor)]
        if not _tape.tape_enabled() and any(
                isinstance(t._data, _jax.core.Tracer) for t in in_tensors):
            # Tape-off tracing context (a rematted/pipelined body whose
            # gradient comes from an OUTER jax.vjp over the traced program):
            # the tape vjp below would never run, silently replacing the
            # custom backward with AD-of-forward. Stage the op as a real
            # jax.custom_vjp instead so the outer differentiation uses
            # cls.backward.
            return cls._apply_staged(*args, **kwargs)
        ctx = PyLayerContext()
        with _tape.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        outs = [o if isinstance(o, Tensor) else Tensor(o) for o in outs]

        need_grad = _tape.tape_enabled() and any(not t.stop_gradient for t in in_tensors)
        if need_grad:
            diff_inputs = [t for t in in_tensors if not t.stop_gradient]

            def vjp_fn(cotangents):
                cts = [Tensor(c) for c in cotangents]
                with _tape.no_grad():
                    grads = cls.backward(ctx, *cts)
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                # paddle contract: backward returns one grad per Tensor input,
                # in forward order; grads for stop_gradient inputs are dropped
                out_grads = []
                for i, t in enumerate(in_tensors):
                    if t.stop_gradient:
                        continue
                    g = grads[i] if i < len(grads) else None
                    out_grads.append(
                        None if g is None else (g._data if isinstance(g, Tensor) else jnp.asarray(g))
                    )
                return tuple(out_grads)

            for o in outs:
                o.stop_gradient = False
            _tape.global_tape().record(diff_inputs, outs, vjp_fn, name=cls.__name__)
        return out if isinstance(out, (tuple, list)) else outs[0]


    @classmethod
    def _apply_staged(cls, *args, **kwargs):
        """PyLayer as a real jax.custom_vjp (tape-off tracing contexts:
        recompute bodies, pipeline stages). Tensor-saved state rides the
        custom_vjp residuals; non-tensor ctx attributes ride a closure (set
        once per trace in fwd, read in bwd)."""
        import jax as _jax

        tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        # kwargs Tensors must ALSO become explicit custom_vjp inputs — a
        # tracer closed over from the surrounding rematted body raises
        # CustomVJPException when the outer vjp differentiates through it
        kw_tensor_keys = [k for k, v in kwargs.items() if isinstance(v, Tensor)]
        n_pos = len(tensor_idx)
        ctx_box = []

        def rebuild(arrs):
            full = list(args)
            for k, i in enumerate(tensor_idx):
                full[i] = Tensor(arrs[k])
            kw = dict(kwargs)
            for j, key in enumerate(kw_tensor_keys):
                kw[key] = Tensor(arrs[n_pos + j])
            return full, kw

        def run_forward(arrs):
            ctx = PyLayerContext()
            full, kw = rebuild(arrs)
            with _tape.no_grad():
                out = cls.forward(ctx, *full, **kw)
            multi = isinstance(out, (tuple, list))
            outs = tuple(out) if multi else (out,)
            out_arrays = tuple(
                o._data if isinstance(o, Tensor) else jnp.asarray(o)
                for o in outs)
            return ctx, multi, out_arrays

        @_jax.custom_vjp
        def fn(*arrs):
            _, multi, out_arrays = run_forward(arrs)
            return out_arrays if multi else out_arrays[0]

        def fwd(*arrs):
            ctx, multi, out_arrays = run_forward(arrs)
            saved = tuple(t._data if isinstance(t, Tensor) else t
                          for t in ctx._saved)
            # residuals carry the saved arrays; keeping the trace-time
            # Tensors on the boxed ctx would retain tracers past the trace
            # (bwd rebuilds _saved from residuals)
            ctx._saved = []
            ctx_box.clear()
            ctx_box.append((ctx, multi))
            return (out_arrays if multi else out_arrays[0]), saved

        def bwd(saved, g):
            ctx, multi = ctx_box[0]
            ctx._saved = [Tensor(s) if hasattr(s, "dtype") else s
                          for s in saved]
            gs = g if multi else (g,)
            with _tape.no_grad():
                grads = cls.backward(ctx, *[Tensor(x) for x in gs])
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            out = []
            for k, i in enumerate(tensor_idx):
                gk = grads[k] if k < len(grads) else None
                if gk is None:
                    out.append(jnp.zeros_like(args[i]._data))
                else:
                    out.append(gk._data if isinstance(gk, Tensor)
                               else jnp.asarray(gk))
            # kwargs tensors: backward's positional contract doesn't cover
            # them (same as the tape path) — zero cotangents
            for key in kw_tensor_keys:
                out.append(jnp.zeros_like(kwargs[key]._data))
            return tuple(out)

        fn.defvjp(fwd, bwd)
        res = fn(*([args[i]._data for i in tensor_idx]
                   + [kwargs[k]._data for k in kw_tensor_keys]))
        if isinstance(res, tuple):
            return tuple(Tensor(r) for r in res)
        return Tensor(res)


def is_pylayer_op(x):
    return isinstance(x, PyLayer)


# ---------------------------------------------------- functional autograd
# ref: python/paddle/autograd/functional.py (U) — jacobian/hessian/jvp/vjp.
# TPU-native: direct mappings onto jax's transforms (the reference builds
# these from repeated backward passes).

def _unwrap(xs):
    single = isinstance(xs, Tensor)
    lst = [xs] if single else list(xs)
    return single, [t._data if isinstance(t, Tensor) else jnp.asarray(t)
                    for t in lst]


def _fn_on_arrays(func, single_in):
    def f(*arrays):
        args = [Tensor(a) for a in arrays]
        out = func(args[0]) if single_in else func(*args)
        if isinstance(out, (list, tuple)):
            import jax

            return jax.tree.map(lambda t: t._data, type(out)(out))
        return out._data

    return f


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """ref paddle.autograd.jacobian — d func / d xs via jax.jacrev. For a
    tuple-returning func, returns a tuple of per-output jacobians (each
    with the per-xs structure)."""
    import jax

    single, arrays = _unwrap(xs)
    f = _fn_on_arrays(func, single)
    multi_out = isinstance(jax.eval_shape(f, *arrays), (tuple, list))
    jac = jax.jacrev(f, argnums=tuple(range(len(arrays))))(*arrays)
    if multi_out:
        # jacrev mirrors the OUTPUT structure; each output leaf carries
        # the per-argnum tuple — drop the arg tuple only for single xs
        return tuple(jax.tree.map(Tensor, j[0] if single else j)
                     for j in jac)
    if single:
        return jax.tree.map(Tensor, jac[0])
    return jax.tree.map(Tensor, jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    """ref paddle.autograd.hessian — d² func / d xs² (scalar output)."""
    import jax

    single, arrays = _unwrap(xs)
    f = _fn_on_arrays(func, single)
    hes = jax.hessian(f, argnums=tuple(range(len(arrays))))(*arrays)
    if single:
        return jax.tree.map(Tensor, hes[0][0])
    return jax.tree.map(Tensor, hes)


def jvp(func, xs, v=None):
    """ref paddle.incubate.autograd.jvp: returns (func(xs), J·v)."""
    import jax

    single, arrays = _unwrap(xs)
    f = _fn_on_arrays(func, single)
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        _, tangents = _unwrap(v)
    out, tangent_out = jax.jvp(lambda *a: f(*a), tuple(arrays),
                               tuple(tangents))
    wrap = lambda o: jax.tree.map(Tensor, o)
    return wrap(out), wrap(tangent_out)


def vjp(func, xs, v=None):
    """ref paddle.incubate.autograd.vjp: returns (func(xs), vᵀ·J)."""
    import jax

    single, arrays = _unwrap(xs)
    f = _fn_on_arrays(func, single)
    out, pullback = jax.vjp(f, *arrays)
    if v is None:
        cot = jax.tree.map(jnp.ones_like, out)
    else:
        # rebuild the cotangent with out's exact pytree structure
        _, cots = _unwrap(v)
        treedef = jax.tree.structure(out)
        if treedef.num_leaves != len(cots):
            raise ValueError(
                f"vjp: v has {len(cots)} leaves but func output has "
                f"{treedef.num_leaves}")
        cot = jax.tree.unflatten(treedef, cots)
    grads = pullback(cot)
    wrap = lambda o: jax.tree.map(Tensor, o)
    if single:
        return wrap(out), wrap(grads[0])
    return wrap(out), wrap(list(grads))
