"""paddle.distribution parity (core family; ref: python/paddle/distribution/ (U))."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import random_state
from ..tensor.creation import _as_t
from ..core.op_call import apply


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, jnp.broadcast_shapes(self.loc.shape, self.scale.shape)))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(jnp.square(self.scale), jnp.broadcast_shapes(self.loc.shape, self.scale.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        z = jax.random.normal(random_state.next_key(), shape)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        v = _arr(value)
        var = jnp.square(self.scale)
        return Tensor(-((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale) + jnp.zeros_like(self.loc))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low).astype(jnp.float32)
        self.high = _arr(high).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape, self.high.shape)
        u = jax.random.uniform(random_state.next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v <= self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low) + jnp.zeros(jnp.broadcast_shapes(self.low.shape, self.high.shape)))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _arr(probs).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + self.probs.shape
        return Tensor(jax.random.bernoulli(random_state.next_key(), self.probs, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits).astype(jnp.float32)

    def sample(self, shape=()):
        return Tensor(jax.random.categorical(random_state.next_key(), self.logits, shape=tuple(shape) + self.logits.shape[:-1]))

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha).astype(jnp.float32)
        self.beta = _arr(beta).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        return Tensor(jax.random.beta(random_state.next_key(), self.alpha, self.beta, shape))

    def log_prob(self, value):
        from jax.scipy.special import betaln

        v = _arr(value)
        return Tensor((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v) - betaln(self.alpha, self.beta))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _arr(concentration).astype(jnp.float32)
        self.rate = _arr(rate).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.concentration.shape, self.rate.shape)
        return Tensor(jax.random.gamma(random_state.next_key(), self.concentration, shape) / self.rate)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _arr(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - gammaln(a))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = total_count
        self.probs_arr = _arr(probs).astype(jnp.float32)

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.probs_arr, 1e-30))
        draws = jax.random.categorical(
            random_state.next_key(), logits, shape=tuple(shape) + (self.total_count,) + self.probs_arr.shape[:-1]
        )
        k = self.probs_arr.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(axis=len(tuple(shape)))
        return Tensor(counts)


def kl_divergence(p, q):
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = jnp.square(p.scale / q.scale)
        t1 = jnp.square((p.loc - q.loc) / q.scale)
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        logp = jax.nn.log_softmax(p.logits, axis=-1)
        logq = jax.nn.log_softmax(q.logits, axis=-1)
        return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
        qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
        return Tensor(pp * (jnp.log(pp) - jnp.log(qq)) + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))
    raise NotImplementedError(f"kl_divergence({type(p).__name__}, {type(q).__name__})")
