"""paddle.distribution parity (core family; ref: python/paddle/distribution/ (U))."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import random_state
from ..tensor.creation import _as_t
from ..core.op_call import apply


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, jnp.broadcast_shapes(self.loc.shape, self.scale.shape)))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(jnp.square(self.scale), jnp.broadcast_shapes(self.loc.shape, self.scale.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        z = jax.random.normal(random_state.next_key(), shape)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        v = _arr(value)
        var = jnp.square(self.scale)
        return Tensor(-((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale) + jnp.zeros_like(self.loc))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low).astype(jnp.float32)
        self.high = _arr(high).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape, self.high.shape)
        u = jax.random.uniform(random_state.next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v <= self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low) + jnp.zeros(jnp.broadcast_shapes(self.low.shape, self.high.shape)))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _arr(probs).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + self.probs.shape
        return Tensor(jax.random.bernoulli(random_state.next_key(), self.probs, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits).astype(jnp.float32)

    def sample(self, shape=()):
        return Tensor(jax.random.categorical(random_state.next_key(), self.logits, shape=tuple(shape) + self.logits.shape[:-1]))

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha).astype(jnp.float32)
        self.beta = _arr(beta).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        return Tensor(jax.random.beta(random_state.next_key(), self.alpha, self.beta, shape))

    def log_prob(self, value):
        from jax.scipy.special import betaln

        v = _arr(value)
        return Tensor((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v) - betaln(self.alpha, self.beta))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _arr(concentration).astype(jnp.float32)
        self.rate = _arr(rate).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.concentration.shape, self.rate.shape)
        return Tensor(jax.random.gamma(random_state.next_key(), self.concentration, shape) / self.rate)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _arr(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - gammaln(a))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = total_count
        self.probs_arr = _arr(probs).astype(jnp.float32)

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.probs_arr, 1e-30))
        draws = jax.random.categorical(
            random_state.next_key(), logits, shape=tuple(shape) + (self.total_count,) + self.probs_arr.shape[:-1]
        )
        k = self.probs_arr.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(axis=len(tuple(shape)))
        return Tensor(counts)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(
            self.loc, jnp.broadcast_shapes(self.loc.shape, self.scale.shape)))

    @property
    def variance(self):
        return Tensor(2 * jnp.square(self.scale) + jnp.zeros_like(self.loc))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        u = jax.random.uniform(random_state.next_key(), shape,
                               minval=-0.5 + 1e-7, maxval=0.5)
        return Tensor(self.loc - self.scale * jnp.sign(u)
                      * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale) + jnp.zeros_like(self.loc))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    _EULER = 0.5772156649015329

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * self._EULER)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * jnp.square(self.scale)
                      + jnp.zeros_like(self.loc))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        g = jax.random.gumbel(random_state.next_key(), shape)
        return Tensor(self.loc + self.scale * g)

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.log(self.scale) + 1 + self._EULER
                      + jnp.zeros_like(self.loc))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate).astype(jnp.float32)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / jnp.square(self.rate))

    def sample(self, shape=()):
        shape = tuple(shape) + self.rate.shape
        e = jax.random.exponential(random_state.next_key(), shape)
        return Tensor(e / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs = _arr(probs).astype(jnp.float32)

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / jnp.square(self.probs))

    def sample(self, shape=()):
        shape = tuple(shape) + self.probs.shape
        u = jax.random.uniform(random_state.next_key(), shape,
                               minval=1e-7, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        k = _arr(value)
        return Tensor(k * jnp.log1p(-self.probs) + jnp.log(self.probs))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + jnp.square(self.scale) / 2))

    @property
    def variance(self):
        s2 = jnp.square(self.scale)
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        z = jax.random.normal(random_state.next_key(), shape)
        return Tensor(jnp.exp(self.loc + self.scale * z))

    def log_prob(self, value):
        v = _arr(value)
        logv = jnp.log(v)
        return Tensor(-jnp.square(logv - self.loc)
                      / (2 * jnp.square(self.scale))
                      - jnp.log(self.scale) - logv
                      - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(self.loc + 0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate).astype(jnp.float32)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def sample(self, shape=()):
        shape = tuple(shape) + self.rate.shape
        k = jax.random.poisson(random_state.next_key(), self.rate, shape)
        return Tensor(k.astype(jnp.float32))

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        k = _arr(value)
        return Tensor(k * jnp.log(self.rate) - self.rate - gammaln(k + 1))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        u = jax.random.uniform(random_state.next_key(), shape,
                               minval=1e-7, maxval=1 - 1e-7)
        return Tensor(self.loc + self.scale * jnp.tan(math.pi * (u - 0.5)))

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(-jnp.log1p(jnp.square(z)) - math.log(math.pi)
                      - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.log(4 * math.pi * self.scale)
                      + jnp.zeros_like(self.loc))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _arr(df).astype(jnp.float32)
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape)
        t = jax.random.t(random_state.next_key(), self.df, shape)
        return Tensor(self.loc + self.scale * t)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        z = (_arr(value) - self.loc) / self.scale
        d = self.df
        return Tensor(gammaln((d + 1) / 2) - gammaln(d / 2)
                      - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                      - (d + 1) / 2 * jnp.log1p(jnp.square(z) / d))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _arr(concentration).astype(jnp.float32)

    @property
    def mean(self):
        c = self.concentration
        return Tensor(c / jnp.sum(c, axis=-1, keepdims=True))

    def sample(self, shape=()):
        # jax.random.dirichlet wants shape == sample_shape + batch_shape
        full = tuple(shape) + self.concentration.shape[:-1]
        out = jax.random.dirichlet(random_state.next_key(),
                                   self.concentration, full)
        return Tensor(out)

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _arr(value)
        c = self.concentration
        norm = gammaln(jnp.sum(c, axis=-1)) - jnp.sum(gammaln(c), axis=-1)
        return Tensor(norm + jnp.sum((c - 1) * jnp.log(v), axis=-1))


# --------------------------------------------------------------- transforms

class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    def forward(self, x):
        return Tensor(self.loc + self.scale * _arr(x))

    def inverse(self, y):
        return Tensor((_arr(y) - self.loc) / self.scale)

    def forward_log_det_jacobian(self, x):
        return Tensor(jnp.broadcast_to(jnp.log(jnp.abs(self.scale)),
                                       _arr(x).shape))


class ExpTransform(Transform):
    def forward(self, x):
        return Tensor(jnp.exp(_arr(x)))

    def inverse(self, y):
        return Tensor(jnp.log(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(_arr(x))


class SigmoidTransform(Transform):
    def forward(self, x):
        return Tensor(jax.nn.sigmoid(_arr(x)))

    def inverse(self, y):
        v = _arr(y)
        return Tensor(jnp.log(v) - jnp.log1p(-v))

    def forward_log_det_jacobian(self, x):
        v = _arr(x)
        return Tensor(-jax.nn.softplus(-v) - jax.nn.softplus(v))


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms, name=None):
        self.base = base
        self.transforms = (transforms if isinstance(transforms, (list, tuple))
                           else [transforms])

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = _arr(value)
        log_det = jnp.zeros_like(y)
        x = Tensor(y)
        for t in reversed(self.transforms):
            x_prev = t.inverse(x)
            log_det = log_det + _arr(t.forward_log_det_jacobian(x_prev))
            x = x_prev
        return Tensor(_arr(self.base.log_prob(x)) - log_det)


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a custom KL rule (ref register_kl)."""

    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn  # noqa: PTA402 -- import-time rule registry
        return fn

    return decorator


def kl_divergence(p, q):
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            return fn(p, q)
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = jnp.square(p.scale / q.scale)
        t1 = jnp.square((p.loc - q.loc) / q.scale)
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        logp = jax.nn.log_softmax(p.logits, axis=-1)
        logq = jax.nn.log_softmax(q.logits, axis=-1)
        return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
        qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
        return Tensor(pp * (jnp.log(pp) - jnp.log(qq)) + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))
    if isinstance(p, Exponential) and isinstance(q, Exponential):
        r = p.rate / q.rate
        return Tensor(jnp.log(r) + q.rate / p.rate - 1)
    if isinstance(p, Laplace) and isinstance(q, Laplace):
        d = jnp.abs(p.loc - q.loc)
        r = p.scale / q.scale
        return Tensor(-jnp.log(r) + d / q.scale
                      + r * jnp.exp(-d / p.scale) - 1)
    raise NotImplementedError(f"kl_divergence({type(p).__name__}, {type(q).__name__})")


class ExponentialFamily(Distribution):
    """Base class for exponential-family distributions (ref
    distribution/exponential_family.py (U)): provides the Bregman-divergence
    entropy identity for subclasses defining natural parameters."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    def entropy(self):
        import jax

        nat = self._natural_parameters
        log_norm = self._log_normalizer(*nat)
        grads = jax.grad(
            lambda *n: jnp.sum(self._log_normalizer(*n)), argnums=tuple(
                range(len(nat))))(*nat)
        ent = log_norm
        for n, g in zip(nat, grads):
            ent = ent - n * g
        return Tensor(ent)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _arr(total_count).astype(jnp.float32)
        self.probs = _arr(probs).astype(jnp.float32)

    def sample(self, shape=()):
        n = int(jnp.max(self.total_count))
        shape_full = tuple(shape) + jnp.broadcast_shapes(
            self.total_count.shape, self.probs.shape)
        u = jax.random.uniform(random_state.next_key(),
                               (n,) + shape_full)
        draws = (u < self.probs).astype(jnp.float32)
        mask = jnp.arange(n).reshape((n,) + (1,) * len(shape_full)) \
            < self.total_count
        return Tensor(jnp.sum(draws * mask, axis=0))

    def log_prob(self, value):
        v = _arr(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        n = self.total_count
        comb = (jax.scipy.special.gammaln(n + 1)
                - jax.scipy.special.gammaln(v + 1)
                - jax.scipy.special.gammaln(n - v + 1))
        return Tensor(comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _arr(probs).astype(jnp.float32)
        self._lims = lims

    def _log_norm_const(self):
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        near_half = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near_half, 0.4, p)
        c = jnp.log((2 * jnp.arctanh(1 - 2 * safe)) / (1 - 2 * safe))
        # Taylor expansion around 1/2 (the reference's lims workaround)
        taylor = jnp.log(2.0) + 4.0 / 3.0 * jnp.square(p - 0.5)
        return jnp.where(near_half, taylor, c)

    def log_prob(self, value):
        v = _arr(value)
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                      + self._log_norm_const())

    def sample(self, shape=()):
        shape = tuple(shape) + self.probs.shape
        u = jax.random.uniform(random_state.next_key(), shape)
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        near_half = jnp.abs(p - 0.5) < 1e-3
        safe = jnp.where(near_half, 0.4, p)
        s = (jnp.log1p(u * (2 * safe - 1) / (1 - safe)) /
             (jnp.log(safe) - jnp.log1p(-safe)))
        return Tensor(jnp.where(near_half, u, s))


class Independent(Distribution):
    """Reinterprets batch dims of a base distribution as event dims (ref
    distribution/independent.py (U))."""

    def __init__(self, base, reinterpreted_batch_rank=1):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = _arr(self.base.log_prob(value))
        axes = tuple(range(-self.rank, 0))
        return Tensor(jnp.sum(lp, axis=axes))

    def entropy(self):
        e = _arr(self.base.entropy())
        return Tensor(jnp.sum(e, axis=tuple(range(-self.rank, 0))))


class MultivariateNormal(Distribution):
    """Multivariate normal over R^k (ref: distribution/multivariate_normal.py
    (U)). Parameterized by exactly one of covariance_matrix /
    precision_matrix / scale_tril; everything routes through the Cholesky
    factor L (cov = L L^T), which is both the numerically stable and the
    MXU-friendly form (triangular solves + one matmul per sample)."""

    @staticmethod
    def _to_tril(mat, kind):
        if kind == "tril":
            return mat
        if kind == "cov":
            return jnp.linalg.cholesky(mat)
        # chol(P) = lower factor of the precision; cov factor is recovered
        # from its inverse: cov = inv(P) = inv_lp^T inv_lp
        lp = jnp.linalg.cholesky(mat)
        eye = jnp.eye(lp.shape[-1], dtype=lp.dtype)
        inv_lp = jax.scipy.linalg.solve_triangular(lp, eye, lower=True)
        return jnp.linalg.cholesky(jnp.swapaxes(inv_lp, -1, -2) @ inv_lp)

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        given = [a is not None
                 for a in (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError(
                "exactly one of covariance_matrix, precision_matrix, "
                "scale_tril must be specified")
        # originals kept as Tensors so rsample can trace through them
        # (pathwise/reparameterized gradients reach loc and the matrix)
        self._loc_in = _as_t(loc)
        if scale_tril is not None:
            self._mat_in, self._mat_kind = _as_t(scale_tril), "tril"
        elif covariance_matrix is not None:
            self._mat_in, self._mat_kind = _as_t(covariance_matrix), "cov"
        else:
            self._mat_in, self._mat_kind = _as_t(precision_matrix), "prec"
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale_tril = self._to_tril(
            _arr(self._mat_in).astype(jnp.float32), self._mat_kind)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(
            self.loc, jnp.broadcast_shapes(
                self.loc.shape, self.scale_tril.shape[:-1])))

    @property
    def covariance_matrix(self):
        return Tensor(self.scale_tril
                      @ jnp.swapaxes(self.scale_tril, -1, -2))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            jnp.sum(jnp.square(self.scale_tril), axis=-1),
            jnp.broadcast_shapes(self.loc.shape,
                                 self.scale_tril.shape[:-1])))

    def sample(self, shape=()):
        # plain Monte-Carlo draw: detached (no tape node), from the
        # precomputed raw-array factor — rsample is the pathwise variant
        batch = jnp.broadcast_shapes(self.loc.shape[:-1],
                                     self.scale_tril.shape[:-2])
        k = self.loc.shape[-1]
        full = tuple(shape) + batch + (k,)
        z = jax.random.normal(random_state.next_key(), full)
        return Tensor(self.loc + jnp.squeeze(
            self.scale_tril @ z[..., None], -1))

    def rsample(self, shape=()):
        batch = jnp.broadcast_shapes(self.loc.shape[:-1],
                                     self.scale_tril.shape[:-2])
        k = self.loc.shape[-1]
        full = tuple(shape) + batch + (k,)
        z = jax.random.normal(random_state.next_key(), full)
        kind = self._mat_kind

        def f(locv, matv):
            tril = MultivariateNormal._to_tril(
                matv.astype(jnp.float32), kind)
            return locv.astype(jnp.float32) \
                + jnp.squeeze(tril @ z[..., None], -1)

        return apply(f, self._loc_in, self._mat_in, _op_name="mvn_rsample")

    def log_prob(self, value):
        v = _arr(value).astype(jnp.float32)
        k = self.loc.shape[-1]
        diff = v - self.loc
        # solve L y = diff; M = ||y||^2 is the Mahalanobis distance
        y = jax.scipy.linalg.solve_triangular(
            jnp.broadcast_to(
                self.scale_tril,
                jnp.broadcast_shapes(self.scale_tril.shape,
                                     diff.shape[:-1] + (k, k))),
            diff[..., None], lower=True)[..., 0]
        m = jnp.sum(jnp.square(y), axis=-1)
        half_logdet = jnp.sum(jnp.log(
            jnp.diagonal(self.scale_tril, axis1=-2, axis2=-1)), axis=-1)
        return Tensor(-0.5 * (m + k * math.log(2 * math.pi)) - half_logdet)

    def entropy(self):
        k = self.loc.shape[-1]
        half_logdet = jnp.sum(jnp.log(
            jnp.diagonal(self.scale_tril, axis1=-2, axis2=-1)), axis=-1)
        batch = jnp.broadcast_shapes(self.loc.shape[:-1],
                                     self.scale_tril.shape[:-2])
        return Tensor(jnp.broadcast_to(
            0.5 * k * (1 + math.log(2 * math.pi)) + half_logdet, batch))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    k = p.loc.shape[-1]
    lq = q.scale_tril
    lp = p.scale_tril
    eye_bcast = jnp.broadcast_shapes(lq.shape, lp.shape)
    # tr(Sigma_q^-1 Sigma_p) = ||Lq^-1 Lp||_F^2
    a = jax.scipy.linalg.solve_triangular(
        jnp.broadcast_to(lq, eye_bcast), jnp.broadcast_to(lp, eye_bcast),
        lower=True)
    tr = jnp.sum(jnp.square(a), axis=(-2, -1))
    diff = q.loc - p.loc
    y = jax.scipy.linalg.solve_triangular(
        jnp.broadcast_to(lq, jnp.broadcast_shapes(
            lq.shape, diff.shape[:-1] + (k, k))),
        diff[..., None], lower=True)[..., 0]
    m = jnp.sum(jnp.square(y), axis=-1)
    hld_p = jnp.sum(jnp.log(jnp.diagonal(lp, axis1=-2, axis2=-1)), axis=-1)
    hld_q = jnp.sum(jnp.log(jnp.diagonal(lq, axis1=-2, axis2=-1)), axis=-1)
    return Tensor(0.5 * (tr + m - k) + hld_q - hld_p)
