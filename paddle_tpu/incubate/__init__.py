"""paddle.incubate parity: fused-op entry points (ref: python/paddle/incubate/
nn/functional/ (U), SURVEY.md §2.2 P25). On TPU the "fused" implementations
are the Pallas kernels in paddle_tpu.ops plus XLA's automatic fusion."""

from . import autograd
from . import nn
from ..ops.softmax_mask_fuse import softmax_mask_fuse, softmax_mask_fuse_upper_triangle

# graph/segment entry points (the reference exposes these under
# paddle.incubate; the implementations live in paddle_tpu.geometric)
from ..geometric import (  # noqa: E402,F401
    segment_sum, segment_mean, segment_max, segment_min,
)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop neighbor sampling over a CSC graph (ref incubate operator).
    Host-side (geometry is data-dependent, like the reference's CPU/GPU
    sampler), returns the reindexed subgraph."""
    import numpy as np

    from ..core.tensor import Tensor
    from ..tensor.creation import _as_t

    rown = np.asarray(_as_t(row)._data)
    cp = np.asarray(_as_t(colptr)._data)
    nodes = np.asarray(_as_t(input_nodes)._data).reshape(-1)
    rng = np.random.default_rng()  # fresh sample every call, like the ref op
    layers = [nodes]
    edges_src, edges_dst = [], []
    frontier = nodes
    for k in sample_sizes:
        nxt = []
        for v in frontier:
            neigh = rown[cp[v]:cp[v + 1]]
            if len(neigh) > k:
                neigh = rng.choice(neigh, size=k, replace=False)
            for u in neigh:
                edges_src.append(u)
                edges_dst.append(v)
            nxt.extend(neigh.tolist())
        frontier = np.unique(np.asarray(nxt, rown.dtype))
        layers.append(frontier)
    uniq = np.unique(np.concatenate(layers))
    remap = {int(u): i for i, u in enumerate(uniq)}
    src = np.asarray([remap[int(u)] for u in edges_src], np.int32)
    dst = np.asarray([remap[int(v)] for v in edges_dst], np.int32)
    return (Tensor(src), Tensor(dst), Tensor(uniq.astype(np.int32)),
            Tensor(np.arange(len(src), dtype=np.int32)) if return_eids
            else Tensor(uniq.astype(np.int32)))


def identity_loss(x, reduction="none"):
    """ref incubate.identity_loss: marks x as a loss (optionally reduced)."""
    from ..tensor.math import mean as _mean, sum as _sum

    if reduction in (0, "sum"):
        return _sum(x)
    if reduction in (1, "mean"):
        return _mean(x)
    return x


class LookAhead:
    """Lookahead wrapper (ref incubate.LookAhead): k inner steps, then slow
    weights interpolate toward fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._slow = None
        self._steps = 0

    def _params(self):
        return [p for p in self.inner._parameter_list if p.trainable]

    def step(self):
        import jax.numpy as jnp

        if self._slow is None:
            self._slow = [p._data for p in self._params()]
        self.inner.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p, slow in zip(self._params(), self._slow):
                new_slow = slow + self.alpha * (p._data - slow)
                p._data = new_slow.astype(p._data.dtype)
            self._slow = [p._data for p in self._params()]

    def clear_grad(self, *a, **k):
        self.inner.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self.inner, name)


class ModelAverage:
    """EMA of parameters applied at eval (ref incubate.ModelAverage):
    accumulate during training, apply()/restore() around evaluation."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = [p for p in (parameters or []) if p.trainable]
        self._acc = [p._data.astype("float32") * 0 for p in self._params]
        self._n = 0
        self._backup = None

    def step(self):
        self._acc = [a + p._data.astype("float32")
                     for a, p in zip(self._acc, self._params)]
        self._n += 1

    def apply(self, executor=None, need_restore=True):
        import contextlib

        self._backup = [p._data for p in self._params]
        for p, a in zip(self._params, self._acc):
            p._data = (a / max(self._n, 1)).astype(p._data.dtype)
        if need_restore:
            outer = self

            @contextlib.contextmanager
            def ctx():
                try:
                    yield
                finally:
                    outer.restore()

            return ctx()
        return contextlib.nullcontext()

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._data = b
            self._backup = None
