"""paddle.incubate parity: fused-op entry points (ref: python/paddle/incubate/
nn/functional/ (U), SURVEY.md §2.2 P25). On TPU the "fused" implementations
are the Pallas kernels in paddle_tpu.ops plus XLA's automatic fusion."""

from . import nn
from ..ops.softmax_mask_fuse import softmax_mask_fuse, softmax_mask_fuse_upper_triangle
