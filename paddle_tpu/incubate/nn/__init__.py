from . import functional
from .layer import FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer
