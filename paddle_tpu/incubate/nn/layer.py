"""Fused transformer layers (ref: python/paddle/incubate/nn/layer/
fused_transformer.py (U)) — same API, computing through the fused functional
entry points."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.initializer import XavierUniform, Constant
from . import functional as IF


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5, nranks=1,
                 ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self._transpose_qkv_wb = transpose_qkv_wb
        if transpose_qkv_wb:
            self.qkv_weight = self.create_parameter([embed_dim, 3 * embed_dim],
                                                    attr=qkv_weight_attr,
                                                    default_initializer=XavierUniform())
            self.qkv_bias = self.create_parameter([3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        else:
            self.qkv_weight = self.create_parameter([3, num_heads, self.head_dim, embed_dim],
                                                    attr=qkv_weight_attr,
                                                    default_initializer=XavierUniform())
            self.qkv_bias = self.create_parameter([3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim], attr=linear_weight_attr,
                                                   default_initializer=XavierUniform())
        self.linear_bias = self.create_parameter([embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter([embed_dim], attr=pre_ln_scale_attr,
                                                  default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter([embed_dim], attr=ln_scale_attr,
                                              default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate, training=self.training,
            num_heads=self.num_heads, transpose_qkv_wb=self._transpose_qkv_wb,
        )


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._activation = activation
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = dropout_rate if act_dropout_rate is None else act_dropout_rate
        self._epsilon = epsilon
        self._normalize_before = normalize_before
        self.linear1_weight = self.create_parameter([d_model, dim_feedforward],
                                                    attr=linear1_weight_attr,
                                                    default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter([dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter([dim_feedforward, d_model],
                                                    attr=linear2_weight_attr,
                                                    default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter([d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter([d_model], attr=ln1_scale_attr,
                                               default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], attr=ln1_bias_attr, is_bias=True)
        self.ln2_scale = self.create_parameter([d_model], attr=ln2_scale_attr,
                                               default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], attr=ln2_bias_attr, is_bias=True)

    def forward(self, src, cache=None):
        return IF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight, self.linear1_bias,
            self.linear2_bias, self.ln1_scale, self.ln1_bias, self.ln2_scale,
            self.ln2_bias, self._act_dropout_rate, self._dropout_rate,
            self._activation, self._epsilon, self._epsilon,
            self._normalize_before, self.training,
        )


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate if attn_dropout_rate is None else attn_dropout_rate,
            normalize_before=normalize_before,
        )
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=dropout_rate if act_dropout_rate is None else act_dropout_rate,
            normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)
