"""Fused functional entry points (ref: python/paddle/incubate/nn/functional/
(U): fused_multi_head_attention, fused_feedforward, fused_rotary_position_
embedding, fused_rms_norm, fused_layer_norm, fused_linear, ...).

TPU stance: "fused" = routed through the Pallas kernel layer (paddle_tpu.ops)
or expressed so XLA's fusion pass emits one kernel. Signatures mirror the
reference so incubate users can switch without edits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_call import apply
from ...core.tensor import Tensor
from ...tensor.creation import _as_t
from ...nn import functional as F


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    if transpose_weight:
        from ...tensor.manipulation import t as _t

        weight = _t(weight)
    return F.linear(x, weight, bias)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False, activation="gelu"):
    from ...tensor.math import matmul

    out = matmul(x, y, transpose_x=trans_x, transpose_y=trans_y)
    out = out + bias
    return getattr(F, activation)(out)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False, name=None):
    from ...tensor.math import matmul

    out = matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None,
                   act_method="gelu", **kw):
    if bias is not None:
        x = x + bias
    return getattr(F, act_method)(x)


def fused_linear_cross_entropy(x, weight, label, ignore_index=-100,
                               transpose_weight=False, chunk_rows=2048,
                               reduction="mean", name=None):
    """LM-head matmul + softmax-CE without materialising [N, vocab] logits
    (chunked scan + rematerialised backward — see ops/fused_ce.py)."""
    from ...ops.fused_ce import fused_linear_cross_entropy as _impl

    def f(h, w, y):
        return _impl(h, w, y, ignore_index=ignore_index,
                     transpose_weight=transpose_weight,
                     chunk_rows=chunk_rows, reduction=reduction)

    return apply(f, _as_t(x), _as_t(weight), _as_t(label).detach(),
                 _op_name="fused_linear_cross_entropy")


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, residual_alpha=1.0,
                     begin_norm_axis=1, bias=None, residual=None, quant_scale=-1, **kw):
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual * residual_alpha
    x_t = _as_t(x)
    norm_shape = tuple(x_t.shape[begin_norm_axis:])
    out = F.layer_norm(x_t, list(norm_shape), norm_weight, norm_bias, epsilon)
    return out


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=1,
                   bias=None, residual=None, **kw):
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
    from ...ops.rms_norm import rms_norm as pallas_rms

    return pallas_rms(x, norm_weight, norm_bias, epsilon, begin_norm_axis)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding parity.
    q/k/v: [batch, seq, heads, head_dim]."""
    from ...ops.rope import apply_rotary_emb

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        outs.append(apply_rotary_emb(t, sin=sin, cos=cos, position_ids=position_ids,
                                     neox=use_neox_rotary_style, base=rotary_emb_base))
    return tuple(outs)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=None, transpose_qkv_wb=False,
                               name=None):
    """Fused MHA block parity (ref: fused_attention_op.cu behavior): optional
    pre-LN -> qkv -> flash attention -> out proj -> dropout -> residual (+LN)."""
    x = _as_t(x)
    residual = x
    if pre_layer_norm:
        ln_shape = [x.shape[-1]]
        x = F.layer_norm(x, ln_shape, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkvw = _as_t(qkv_weight)
    b, s, e = x.shape
    if transpose_qkv_wb:
        # weight [e, 3e]
        qkv = F.linear(x, qkvw, qkv_bias)
        n_heads = num_heads
        head_dim = e // n_heads
        qkv_r = qkv.reshape([b, s, 3, n_heads, head_dim])
    else:
        # weight [3, n_heads, head_dim, e]
        n_heads = qkvw.shape[1]
        head_dim = qkvw.shape[2]
        from ...tensor.einsum import einsum

        qkv_r = einsum("bse,tnde->bstnd", x, qkvw)
        if qkv_bias is not None:
            qkv_r = qkv_r + _as_t(qkv_bias).reshape([1, 1, 3, n_heads, head_dim])
    q = qkv_r[:, :, 0]
    k = qkv_r[:, :, 1]
    v = qkv_r[:, :, 2]
    ctx = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                         dropout_p=attn_dropout_rate, training=training)
    ctx = ctx.reshape([b, s, n_heads * head_dim])
    out = F.linear(ctx, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      ring_id=-1, name=None):
    x = _as_t(x)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    out = F.linear(x, linear1_weight, linear1_bias)
    out = getattr(F, activation)(out)
    out = F.dropout(out, dropout1_rate, training=training, mode=mode)
    out = F.linear(out, linear2_weight, linear2_bias)
    out = F.dropout(out, dropout2_rate, training=training, mode=mode)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_multi_transformer(*args, **kwargs):
    raise NotImplementedError(
        "fused_multi_transformer (inference generation loop) lands with the "
        "serving path; use models.gpt with cache-based decode meanwhile"
    )


def masked_multihead_attention(*args, **kwargs):
    raise NotImplementedError("use F.scaled_dot_product_attention with a mask")


def swiglu(x, y=None, name=None):
    """SwiGLU gate (ref: incubate/nn/functional/swiglu.py (U)): silu(x) * y;
    with y=None, x is split in half along the last axis. One fused XLA
    kernel — the same composition the LLaMA models here train with."""
    x = _as_t(x)
    if y is None:
        from ...tensor.manipulation import chunk

        x, y = chunk(x, 2, axis=-1)
    else:
        y = _as_t(y)
    return apply(lambda a, b: jax.nn.silu(a) * b, x, y, _op_name="swiglu")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu"):
    """Dense expert-computation MoE (ref: incubate fused_ec_moe (U)):
    out[t] = sum_e softmax(gate[t])_e * FFN_e(x[t]). Every token visits
    every expert — the einsum batches all expert FFNs into two large MXU
    matmuls; no scatter/gather kernels needed on TPU."""
    if act_type not in ("gelu", "relu", "silu"):
        raise ValueError(
            f"fused_ec_moe: unsupported act_type {act_type!r} "
            "(expected 'gelu', 'relu' or 'silu')")
    x = _as_t(x)
    gate = _as_t(gate)
    w0, b0 = _as_t(bmm0_weight), _as_t(bmm0_bias)
    w1, b1 = _as_t(bmm1_weight), _as_t(bmm1_bias)

    def f(xv, gv, w0v, b0v, w1v, b1v):
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "silu": jax.nn.silu}[act_type]
        # reference bias shape is [e, 1, f]; flatten to [e, f] so it
        # broadcasts against the expert axis, not sequence
        b0f = b0v.reshape(b0v.shape[0], b0v.shape[-1])
        b1f = b1v.reshape(b1v.shape[0], b1v.shape[-1])
        probs = jax.nn.softmax(gv, axis=-1)             # [b, s, e]
        h = jnp.einsum("bsd,edf->bsef", xv, w0v) + b0f  # [b, s, e, f]
        h = act(h)
        o = jnp.einsum("bsef,efd->bsed", h, w1v) + b1f  # [b, s, e, d]
        return jnp.einsum("bsed,bse->bsd", o, probs)

    return apply(f, x, gate, w0, b0, w1, b1, _op_name="fused_ec_moe")
