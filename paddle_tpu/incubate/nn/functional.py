"""Fused functional entry points (ref: python/paddle/incubate/nn/functional/
(U): fused_multi_head_attention, fused_feedforward, fused_rotary_position_
embedding, fused_rms_norm, fused_layer_norm, fused_linear, ...).

TPU stance: "fused" = routed through the Pallas kernel layer (paddle_tpu.ops)
or expressed so XLA's fusion pass emits one kernel. Signatures mirror the
reference so incubate users can switch without edits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.op_call import apply
from ...core.tensor import Tensor
from ...tensor.creation import _as_t
from ...nn import functional as F


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    if transpose_weight:
        from ...tensor.manipulation import t as _t

        weight = _t(weight)
    return F.linear(x, weight, bias)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False, activation="gelu"):
    from ...tensor.math import matmul

    out = matmul(x, y, transpose_x=trans_x, transpose_y=trans_y)
    out = out + bias
    return getattr(F, activation)(out)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False, name=None):
    from ...tensor.math import matmul

    out = matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None,
                   act_method="gelu", **kw):
    if bias is not None:
        x = x + bias
    return getattr(F, act_method)(x)


def fused_linear_cross_entropy(x, weight, label, ignore_index=-100,
                               transpose_weight=False, chunk_rows=2048,
                               reduction="mean", name=None):
    """LM-head matmul + softmax-CE without materialising [N, vocab] logits
    (chunked scan + rematerialised backward — see ops/fused_ce.py)."""
    from ...ops.fused_ce import fused_linear_cross_entropy as _impl

    def f(h, w, y):
        return _impl(h, w, y, ignore_index=ignore_index,
                     transpose_weight=transpose_weight,
                     chunk_rows=chunk_rows, reduction=reduction)

    return apply(f, _as_t(x), _as_t(weight), _as_t(label).detach(),
                 _op_name="fused_linear_cross_entropy")


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, residual_alpha=1.0,
                     begin_norm_axis=1, bias=None, residual=None, quant_scale=-1, **kw):
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual * residual_alpha
    x_t = _as_t(x)
    norm_shape = tuple(x_t.shape[begin_norm_axis:])
    out = F.layer_norm(x_t, list(norm_shape), norm_weight, norm_bias, epsilon)
    return out


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=1,
                   bias=None, residual=None, **kw):
    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
    from ...ops.rms_norm import rms_norm as pallas_rms

    return pallas_rms(x, norm_weight, norm_bias, epsilon, begin_norm_axis)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding parity.
    q/k/v: [batch, seq, heads, head_dim]."""
    from ...ops.rope import apply_rotary_emb

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        outs.append(apply_rotary_emb(t, sin=sin, cos=cos, position_ids=position_ids,
                                     neox=use_neox_rotary_style, base=rotary_emb_base))
    return tuple(outs)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=None, transpose_qkv_wb=False,
                               name=None):
    """Fused MHA block parity (ref: fused_attention_op.cu behavior): optional
    pre-LN -> qkv -> flash attention -> out proj -> dropout -> residual (+LN)."""
    x = _as_t(x)
    residual = x
    if pre_layer_norm:
        ln_shape = [x.shape[-1]]
        x = F.layer_norm(x, ln_shape, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkvw = _as_t(qkv_weight)
    b, s, e = x.shape
    if transpose_qkv_wb:
        # weight [e, 3e]
        qkv = F.linear(x, qkvw, qkv_bias)
        n_heads = num_heads
        head_dim = e // n_heads
        qkv_r = qkv.reshape([b, s, 3, n_heads, head_dim])
    else:
        # weight [3, n_heads, head_dim, e]
        n_heads = qkvw.shape[1]
        head_dim = qkvw.shape[2]
        from ...tensor.einsum import einsum

        qkv_r = einsum("bse,tnde->bstnd", x, qkvw)
        if qkv_bias is not None:
            qkv_r = qkv_r + _as_t(qkv_bias).reshape([1, 1, 3, n_heads, head_dim])
    q = qkv_r[:, :, 0]
    k = qkv_r[:, :, 1]
    v = qkv_r[:, :, 2]
    ctx = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                         dropout_p=attn_dropout_rate, training=training)
    ctx = ctx.reshape([b, s, n_heads * head_dim])
    out = F.linear(ctx, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      ring_id=-1, name=None):
    x = _as_t(x)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    out = F.linear(x, linear1_weight, linear1_bias)
    out = getattr(F, activation)(out)
    out = F.dropout(out, dropout1_rate, training=training, mode=mode)
    out = F.linear(out, linear2_weight, linear2_bias)
    out = F.dropout(out, dropout2_rate, training=training, mode=mode)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def _cache_attend(q, cache_k, cache_v, upto, maskv, max_seq):
    """Attend q [b,s,h,d] over a fixed-capacity cache [b,h,max_seq,d],
    valid positions <= upto ([b] or scalar int), optional additive mask
    (padded with zeros out to max_seq). fp32 softmax. Shared by
    masked_multihead_attention and fused_multi_transformer's decode
    branch so the cache semantics cannot drift."""
    import math as _math

    head_dim = q.shape[-1]
    scores = jnp.einsum("bshd,bhtd->bhst", q.astype(jnp.float32),
                        cache_k.astype(jnp.float32))
    scores = scores / _math.sqrt(head_dim)
    upto = jnp.asarray(upto)
    lens_b = upto.reshape(-1, 1, 1, 1) if upto.ndim else upto
    valid = jnp.arange(max_seq)[None, None, None, :] <= lens_b
    scores = jnp.where(valid, scores, -1e30)
    if maskv is not None:
        m = maskv
        while m.ndim < 4:  # [.., L] -> [b?,h?,s?,L] broadcastable
            m = m[:, None] if m.ndim > 1 else m[None]
        if m.shape[-1] < max_seq:  # upstream masks cover [0, step+1)
            m = jnp.pad(m, ((0, 0),) * (m.ndim - 1)
                        + ((0, max_seq - m.shape[-1]),))
        scores = scores + m[..., :max_seq]
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhst,bhtd->bshd", p, cache_v.astype(jnp.float32))


def _rope_full_table(x, cos, sin, neox):
    """Rotate x [..., d] by FULL-head-dim cos/sin tables broadcastable to
    x's shape (the reference's fused kernels take cos/sin already expanded
    to head_dim — neox duplicates half-tables, GPT-J interleaves). Shared
    by masked_multihead_attention and fused_multi_transformer so the inline
    rope cannot drift from the standalone fused_rope op (ops/rope.py)."""
    xf = x.astype(jnp.float32)
    if neox:
        d = x.shape[-1]
        x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
        rot = jnp.concatenate([-x2, x1], axis=-1)
    else:
        x1, x2 = xf[..., 0::2], xf[..., 1::2]
        rot = jnp.stack([-x2, x1], axis=-1).reshape(xf.shape)
    return (xf * cos.astype(jnp.float32)
            + rot * sin.astype(jnp.float32)).astype(x.dtype)


def _rope_tables_at(rt, positions, head_dim):
    """Slice per-position cos/sin from a packed rotary tensor
    [2, b, 1, max_seq, head_dim] (index 0 = cos, 1 = sin; axis 2 may be
    absent). positions: [b] int — each row's write position. Returns
    (cos, sin) shaped [b, 1, head_dim] (head axis broadcast)."""
    rt = jnp.asarray(rt)
    if rt.ndim == 5:  # [2, b, 1, S, d]
        rt = rt[:, :, 0]
    # rt now [2, b, S, d]
    if rt.shape[-1] != head_dim:
        raise ValueError(
            f"rotary table last dim {rt.shape[-1]} != head_dim {head_dim} "
            "(tables must be FULL head_dim cos/sin)")
    if rt.shape[2] == 1:
        cs = rt[:, :, 0]                          # single-step tables
    else:
        pos = jnp.asarray(positions).reshape(-1, 1, 1)
        cs = jnp.take_along_axis(rt, pos[None].astype(jnp.int32),
                                 axis=2)[:, :, 0]
    return cs[0][:, None, :], cs[1][:, None, :]


def masked_multihead_attention(x, cache_kv=None, src_mask=None, bias=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0, name=None):
    """Single-step decode attention over a fixed-capacity KV cache (ref:
    incubate masked_multihead_attention (U) — the CUDA MMHA kernel behind
    fused generation). TPU stance: the gather/attend/update runs as one
    XLA program; quantization arguments are accepted for signature parity;
    rotary is applied inline (see rotary_tensor below); bias/beam
    arguments raise.

    x: [bsz, 3*num_head*head_dim] packed qkv for ONE new token
    cache_kv: [2, bsz, num_head, max_seq, head_dim]; the step index is
        sequence_lengths ([bsz] int, tokens already cached) or 0
    src_mask: optional additive mask broadcastable to
        [bsz, 1, 1, max_seq] (e.g. -inf at padding)
    rotary_tensor: packed cos/sin tables [2, bsz, 1, max_seq, head_dim]
        (index 0 = cos, 1 = sin, FULL head_dim — the reference kernel's
        inline-rope contract); each row's table is read at its write
        position (sequence_lengths) and applied to q and k before the
        cache write. Requires rotary_emb_dims == 1;
        use_neox_rotary_style picks rotate-half vs interleaved pairs.
    returns (out [bsz, num_head*head_dim], updated cache_kv)
    """
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")
    if rotary_emb_dims not in (0, 1):
        raise NotImplementedError(
            "masked_multihead_attention: rotary_emb_dims must be 0 or 1 "
            "(2-section rope not supported)")
    if rotary_tensor is not None and rotary_emb_dims == 0:
        rotary_emb_dims = 1
    if bias is not None or beam_cache_offset is not None:
        raise NotImplementedError(
            "masked_multihead_attention: bias/beam_cache_offset")
    x = _as_t(x)
    cache = _as_t(cache_kv)
    args = [x, cache]
    if src_mask is not None:
        args.append(_as_t(src_mask).detach())
    if sequence_lengths is not None:
        args.append(_as_t(sequence_lengths).detach())
    if rotary_tensor is not None:
        args.append(_as_t(rotary_tensor).detach())

    n_head = cache.shape[2]
    max_seq = cache.shape[3]
    head_dim = cache.shape[4]

    def f(xv, cachev, *rest):
        ri = 0
        maskv = None
        if src_mask is not None:
            maskv = rest[ri]
            ri += 1
        if sequence_lengths is not None:
            lens = rest[ri].astype(jnp.int32)
            ri += 1
        else:
            lens = jnp.zeros((xv.shape[0],), jnp.int32)
        rot = rest[ri] if rotary_tensor is not None else None
        if not isinstance(lens, jax.core.Tracer) and bool(
                jnp.any(lens >= max_seq)):
            raise ValueError(
                f"masked_multihead_attention: cache full "
                f"(sequence_lengths >= max_seq {max_seq})")
        # Under jit the eager guard above can't fire; a full cache would
        # otherwise silently drop the new token's K/V. Poison the affected
        # ROW with NaN instead so the failure is loud (propagates, and
        # trips jax_debug_nans / FLAGS check_nan_inf when enabled) while
        # still-valid sequences in the batch stay intact.
        overflow = (lens >= max_seq)[:, None]
        b = xv.shape[0]
        qkv = xv.reshape(b, 3, n_head, head_dim)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]   # [b, h, d]
        if rot is not None:
            cosv, sinv = _rope_tables_at(rot, lens, head_dim)  # [b,1,d]
            q = _rope_full_table(q, cosv, sinv, use_neox_rotary_style)
            k = _rope_full_table(k, cosv, sinv, use_neox_rotary_style)
        # write k/v at each row's step index: a single scatter touching
        # one position per row — a where() over the full cache would
        # read+write the whole KV cache every step and defeat donated
        # in-place aliasing (r5 decode trace)
        bidx = jnp.arange(b)
        upd = jnp.stack([k, v], axis=1).astype(cachev.dtype)  # [b,2,h,d]
        new_cache = cachev.at[:, bidx, :, lens].set(upd)
        out = _cache_attend(q[:, None], new_cache[0], new_cache[1], lens,
                            maskv, max_seq)
        out = out.astype(xv.dtype).reshape(b, n_head * head_dim)
        out = jnp.where(overflow, jnp.asarray(jnp.nan, out.dtype), out)
        return out, new_cache

    return apply(f, *args, _op_name="masked_multihead_attention")


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, pre_caches=None,
                            rotary_embs=None, time_step=None, attn_mask=None,
                            dropout_rate=0.0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1,
                            rotary_emb_dims=0, use_neox_rotary_style=False,
                            name=None):
    """Fused multi-layer transformer decoder pass (ref: incubate
    fused_multi_transformer (U) — the CUDA fused generation stack). One
    XLA program runs every layer: pre-LN -> packed qkv -> attention
    (causal prefill, via the flash path when unmasked, WRITING the k/v
    into cache_kvs when given; or single-step decode against cache_kvs at
    time_step) -> out proj -> residual -> ffn. Differentiable through the
    tape (everything routes through apply); pre_caches raises.

    x: [bsz, seq, dim]; qkv_weights[i]: [3, n_head, head_dim, dim] when
    trans_qkvw else [dim, 3, n_head, head_dim];
    cache_kvs[i]: [2, bsz, n_head, max_seq, head_dim].
    rotary_embs: packed cos/sin tables [2, bsz, 1, max_seq, head_dim]
    (index 0 = cos, 1 = sin, FULL head_dim — the reference fused kernel's
    inline-rope contract (U)); applied to q and k in EVERY layer before
    the cache write/attend, at positions [0, seq) in prefill and at
    time_step in decode. use_neox_rotary_style picks rotate-half vs
    interleaved pairs; rotary_emb_dims must be 0 or 1.
    Returns out, or (out, updated cache_kvs) when cache_kvs is given.
    """
    if pre_caches is not None:
        raise NotImplementedError("fused_multi_transformer: pre_caches")
    if rotary_emb_dims not in (0, 1):
        raise NotImplementedError(
            "fused_multi_transformer: rotary_emb_dims must be 0 or 1")
    n_layers = len(qkv_weights)
    decode = cache_kvs is not None and time_step is not None

    weight_lists = [ln_scales, ln_biases, qkv_weights, qkv_biases,
                    linear_weights, linear_biases, ffn_ln_scales,
                    ffn_ln_biases, ffn1_weights, ffn1_biases,
                    ffn2_weights, ffn2_biases]
    # flatten every tensor into apply() args so gradients flow through
    # the tape; record (list_idx, layer_idx) for reconstruction
    flat, layout = [], []
    for li, lst in enumerate(weight_lists):
        for i in range(n_layers):
            t = None if lst is None else lst[i]
            if t is not None:
                layout.append((li, i))
                flat.append(_as_t(t))
    n_caches = len(cache_kvs) if cache_kvs is not None else 0
    cache_args = [_as_t(c).detach() for c in (cache_kvs or [])]
    extra = []
    if decode:
        extra.append(_as_t(time_step).detach())
    if attn_mask is not None:
        extra.append(_as_t(attn_mask).detach())
    if rotary_embs is not None:
        extra.append(_as_t(rotary_embs).detach())

    def f(xv, *rest):
        ws = {k: None for k in
              [(li, i) for li in range(12) for i in range(n_layers)]}
        for (li, i), t in zip(layout, rest[:len(layout)]):
            ws[(li, i)] = t
        off = len(layout)
        caches = list(rest[off:off + n_caches])
        off += n_caches
        ts = None
        overflow = jnp.asarray(False)
        if decode:
            ts = rest[off].astype(jnp.int32).reshape(())
            off += 1
            if caches:
                cap = caches[0].shape[3]
                if not isinstance(ts, jax.core.Tracer):
                    if bool(ts >= cap):
                        raise ValueError(
                            f"fused_multi_transformer: cache full "
                            f"(time_step {int(ts)} >= max_seq {cap})")
                # jit path: the eager guard can't fire, so a full cache
                # poisons the output with NaN (loud under jax_debug_nans /
                # FLAGS check_nan_inf) instead of silently dropping K/V.
                overflow = ts >= cap
        maskv = None
        if attn_mask is not None:
            maskv = rest[off]
            off += 1
        rotv = None
        if rotary_embs is not None:
            rotv = jnp.asarray(rest[off])
            if rotv.ndim == 5:            # [2, b, 1, S, d] -> [2, b, S, d]
                rotv = rotv[:, :, 0]

        def norm(h, scale, bias_):
            mean = jnp.mean(h, axis=-1, keepdims=True)
            var = jnp.var(h, axis=-1, keepdims=True)
            out = (h - mean) * jax.lax.rsqrt(var + epsilon)
            return out * scale + bias_

        acts = {"gelu": lambda a: jax.nn.gelu(a, approximate=False),
                "relu": jax.nn.relu, "silu": jax.nn.silu}
        act = acts[activation]

        def drop(t):
            # reference semantics at BOTH residual adds: upscale_in_train
            # scales kept units by 1/keep in training and is identity at
            # eval; downscale_in_infer masks without scaling in training
            # and multiplies by keep at eval
            if not dropout_rate:
                return t
            keep = 1.0 - dropout_rate
            if not training:
                return t * keep if mode == "downscale_in_infer" else t
            from ...core import random as random_state

            mask_d = jax.random.bernoulli(
                random_state.next_key(), keep, t.shape)
            kept = t / keep if mode == "upscale_in_train" else t
            return jnp.where(mask_d, kept, 0.0)

        h = xv
        b, s, dim = h.shape
        qw0 = ws[(2, 0)]
        if trans_qkvw:
            n_head, head_dim = qw0.shape[1], qw0.shape[2]
        else:
            n_head, head_dim = qw0.shape[2], qw0.shape[3]
        new_caches = []
        for i in range(n_layers):
            residual = h
            ln_in = norm(h, ws[(0, i)], ws[(1, i)]) if pre_layer_norm else h
            qw = ws[(2, i)]
            if trans_qkvw:
                qkv = jnp.einsum("bsd,thed->bsthe", ln_in, qw)
            else:
                qkv = jnp.einsum("bsd,dthe->bsthe", ln_in, qw)
            if ws[(3, i)] is not None:
                qkv = qkv + ws[(3, i)].reshape(1, 1, 3, n_head, head_dim)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b,s,h,d]
            if rotv is not None:
                if decode:
                    pos = jnp.broadcast_to(ts[None], (b,))
                    cosv, sinv = _rope_tables_at(rotv, pos, head_dim)
                    cosv, sinv = cosv[:, None], sinv[:, None]  # [b,1,1,d]
                else:
                    if rotv.shape[2] < s:
                        raise ValueError(
                            f"fused_multi_transformer: rotary table covers "
                            f"{rotv.shape[2]} positions < prefill length "
                            f"{s} (a seq-1 decode table would silently "
                            "broadcast position 0 over every token)")
                    cosv = rotv[0][:, :s, None, :]             # [b,s,1,d]
                    sinv = rotv[1][:, :s, None, :]
                q = _rope_full_table(q, cosv, sinv, use_neox_rotary_style)
                k = _rope_full_table(k, cosv, sinv, use_neox_rotary_style)
            if caches:
                cache = caches[i]
                max_seq = cache.shape[3]
                kk = jnp.transpose(k, (0, 2, 1, 3))   # [b,h,s,d]
                vv = jnp.transpose(v, (0, 2, 1, 3))
                if decode:
                    # single-position dynamic_update_slice: a where() over
                    # the full cache would READ+WRITE the whole KV cache
                    # per layer per step (the r5 decode trace showed 27%
                    # of step time in exactly those copies) and defeat
                    # donated in-place aliasing. DUS clamps out-of-range
                    # starts, so an overflowing time_step must DROP the
                    # write (the pre-r5 where() semantics; the output is
                    # already NaN-poisoned) — select against the one old
                    # slot, not the whole cache.
                    upd = jnp.stack([kk, vv]).astype(cache.dtype)
                    zero = jnp.zeros((), jnp.int32)
                    pos = jnp.minimum(ts.astype(jnp.int32), max_seq - 1)
                    start = (zero, zero, zero, pos, zero)
                    old = jax.lax.dynamic_slice(cache, start, upd.shape)
                    upd = jnp.where(ts < max_seq, upd, old)
                    new_caches.append(jax.lax.dynamic_update_slice(
                        cache, upd, start))
                else:
                    # prefill: write positions [0, s) so later decode
                    # steps attend over the prompt
                    pad = ((0, 0), (0, 0), (0, max_seq - s), (0, 0))
                    inmask = (jnp.arange(max_seq) < s)[None, None, :, None]
                    new_k = jnp.where(inmask, jnp.pad(kk, pad), cache[0])
                    new_v = jnp.where(inmask, jnp.pad(vv, pad), cache[1])
                    new_caches.append(jnp.stack([new_k, new_v]))
            if decode:
                cache_k, cache_v = new_caches[i][0], new_caches[i][1]
                attn = _cache_attend(q, cache_k, cache_v, ts, maskv,
                                     cache_k.shape[2]).astype(h.dtype)
            elif maskv is not None:
                # masked prefill: dense causal scores + additive mask
                scores = jnp.einsum(
                    "bshd,bthd->bhst", q.astype(jnp.float32),
                    k.astype(jnp.float32)) / float(np.sqrt(head_dim))
                causal = jnp.tril(jnp.ones((s, s), bool))
                scores = jnp.where(causal[None, None], scores, -1e30)
                scores = scores + maskv[..., :s]
                pr = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum("bhst,bthd->bshd", pr,
                                  v.astype(jnp.float32)).astype(h.dtype)
            else:
                from ...ops.flash_attention import flash_attention_arrays

                attn = flash_attention_arrays(q, k, v, causal=True)
            attn = attn.reshape(b, s, n_head * head_dim)
            out = attn @ ws[(4, i)]
            if ws[(5, i)] is not None:
                out = out + ws[(5, i)]
            out = drop(out)
            h = residual + out
            if not pre_layer_norm:
                h = norm(h, ws[(0, i)], ws[(1, i)])
            residual = h
            ffn_in = norm(h, ws[(6, i)], ws[(7, i)]) \
                if pre_layer_norm else h
            f1 = ffn_in @ ws[(8, i)]
            if ws[(9, i)] is not None:
                f1 = f1 + ws[(9, i)]
            f2 = act(f1) @ ws[(10, i)]
            if ws[(11, i)] is not None:
                f2 = f2 + ws[(11, i)]
            h = residual + drop(f2)
            if not pre_layer_norm:
                h = norm(h, ws[(6, i)], ws[(7, i)])
        h = jnp.where(overflow, jnp.asarray(jnp.nan, h.dtype), h)
        if caches:
            return (h,) + tuple(new_caches)
        return h

    res = apply(f, _as_t(x), *flat, *cache_args, *extra,
                _op_name="fused_multi_transformer")
    if cache_kvs is not None:
        return res[0], list(res[1:])
    return res


def swiglu(x, y=None, name=None):
    """SwiGLU gate (ref: incubate/nn/functional/swiglu.py (U)): silu(x) * y;
    with y=None, x is split in half along the last axis. One fused XLA
    kernel — the same composition the LLaMA models here train with."""
    x = _as_t(x)
    if y is None:
        from ...tensor.manipulation import chunk

        x, y = chunk(x, 2, axis=-1)
    else:
        y = _as_t(y)
    return apply(lambda a, b: jax.nn.silu(a) * b, x, y, _op_name="swiglu")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu"):
    """Dense expert-computation MoE (ref: incubate fused_ec_moe (U)):
    out[t] = sum_e softmax(gate[t])_e * FFN_e(x[t]). Every token visits
    every expert — the einsum batches all expert FFNs into two large MXU
    matmuls; no scatter/gather kernels needed on TPU."""
    if act_type not in ("gelu", "relu", "silu"):
        raise ValueError(
            f"fused_ec_moe: unsupported act_type {act_type!r} "
            "(expected 'gelu', 'relu' or 'silu')")
    x = _as_t(x)
    gate = _as_t(gate)
    w0, b0 = _as_t(bmm0_weight), _as_t(bmm0_bias)
    w1, b1 = _as_t(bmm1_weight), _as_t(bmm1_bias)

    def f(xv, gv, w0v, b0v, w1v, b1v):
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "silu": jax.nn.silu}[act_type]
        # reference bias shape is [e, 1, f]; flatten to [e, f] so it
        # broadcasts against the expert axis, not sequence
        b0f = b0v.reshape(b0v.shape[0], b0v.shape[-1])
        b1f = b1v.reshape(b1v.shape[0], b1v.shape[-1])
        probs = jax.nn.softmax(gv, axis=-1)             # [b, s, e]
        h = jnp.einsum("bsd,edf->bsef", xv, w0v) + b0f  # [b, s, e, f]
        h = act(h)
        o = jnp.einsum("bsef,efd->bsed", h, w1v) + b1f  # [b, s, e, d]
        return jnp.einsum("bsed,bse->bsd", o, probs)

    return apply(f, x, gate, w0, b0, w1, b1, _op_name="fused_ec_moe")
